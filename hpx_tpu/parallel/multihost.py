"""Multi-host device plane: jax.distributed wiring from the batch env.

Reference analog: SURVEY.md §5.8 — the reference's parcelports
bootstrap from PMI/mpirun; the TPU-native device plane bootstraps from
`jax.distributed` (gRPC over DCN), after which `jax.devices()` spans
every host and one `Mesh` covers the pod. The HOST plane
(dist/runtime.py parcels/actions) is independent and stays per-process.

This module closes the loop with runtime/batch_environments: the same
SLURM/PBS/OpenMPI/TPU-pod detection that configures host localities
also resolves (coordinator, num_processes, process_id) for
jax.distributed, so a pod job needs no explicit flags:

    from hpx_tpu.parallel import multihost
    multihost.init()                     # no-op single-host
    mesh = multihost.global_mesh((None, 8), ("dp", "tp"))

On TPU pods jax.distributed can usually self-configure from the
metadata server; `init()` passes through whatever is resolved and
lets jax fill gaps. Single-process (no batch env, one host) is an
explicit no-op — everything keeps working on local devices.
"""

from __future__ import annotations

import os
from typing import Any, Optional, Sequence, Tuple

__all__ = ["resolve", "init", "global_mesh", "is_initialized"]

_DEFAULT_PORT = 8476     # jax.distributed's conventional default
_initialized = False


def resolve(environ=None) -> Optional[Tuple[Optional[str],
                                            Optional[int],
                                            Optional[int]]]:
    """(coordinator_address, num_processes, process_id) from the batch
    environment, or None when this is a single-process run. Explicit
    JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID env
    vars win over scheduler detection."""
    env = os.environ if environ is None else environ
    exp_coord = env.get("JAX_COORDINATOR_ADDRESS")
    exp_nproc = env.get("JAX_NUM_PROCESSES")
    exp_pid = env.get("JAX_PROCESS_ID")

    from ..runtime.batch_environments import detect
    be = detect(env if environ is not None else None)

    det = None
    if be.name == "tpu":
        # TPU pods: jax.distributed self-configures from the metadata
        # server, so a detected pod worker resolves even when the env
        # lacks hostnames/world size — initialize() fills the gaps
        det = (f"{be.node_list[0]}:{_DEFAULT_PORT}" if be.node_list
               else None, be.num_localities, be.this_locality)
    elif (be.found() and be.num_localities not in (None, 1)
          and be.this_locality is not None):
        det = (f"{be.node_list[0]}:{_DEFAULT_PORT}" if be.node_list
               else None, be.num_localities, be.this_locality)

    if exp_coord or exp_nproc or exp_pid:
        # explicit JAX_* values override field-by-field; scheduler
        # detection fills what the user left unset (a PBS user pinning
        # only the coordinator port must not lose rank/world size)
        d = det or (None, None, None)
        return (exp_coord or d[0],
                int(exp_nproc) if exp_nproc else d[1],
                int(exp_pid) if exp_pid else d[2])
    return det


def init(coordinator_address: Optional[str] = None,
         num_processes: Optional[int] = None,
         process_id: Optional[int] = None,
         environ=None) -> bool:
    """Initialize jax.distributed when this is (or is forced to be) a
    multi-process run; returns True if initialization happened.
    Explicit arguments override resolution; with no resolution and no
    arguments this is a no-op (single host)."""
    global _initialized
    if _initialized:
        return True
    if (coordinator_address is None and num_processes is None
            and process_id is None):
        r = resolve(environ)
        if r is None:
            return False
        coordinator_address, num_processes, process_id = r
    import jax
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id)
    except RuntimeError as e:
        # the user may have initialized jax.distributed directly —
        # that's the state init() exists to reach, not an error
        if "already" not in str(e).lower():
            raise
    _initialized = True
    return True


def is_initialized() -> bool:
    return _initialized


def global_mesh(shape: Optional[Sequence[Optional[int]]] = None,
                axes: Sequence[str] = ("dp",),
                devices: Optional[Sequence[Any]] = None):
    """Mesh over ALL devices jax sees (every host's, once init() ran).
    `shape` may contain one None to infer that axis (numpy -1 style);
    shape=None puts everything on the first axis. Construction goes
    through parallel.mesh.make_mesh so all-device meshes share its
    cache (jit caches keyed on meshes hit across callers)."""
    import numpy as np
    import jax

    from .mesh import make_mesh

    devs = list(devices) if devices is not None else jax.devices()
    n = len(devs)
    if shape is None:
        shape = [n] + [1] * (len(axes) - 1)
    shape = [(-1 if s is None else s) for s in shape]
    if shape.count(-1) > 1:
        raise ValueError("at most one axis may be inferred (None)")
    known = int(np.prod([s for s in shape if s != -1])) or 1
    if -1 in shape:
        if n % known:
            raise ValueError(f"{n} devices not divisible by {known}")
        shape[shape.index(-1)] = n // known
    if int(np.prod(shape)) != n:
        raise ValueError(f"mesh shape {tuple(shape)} != {n} devices")
    return make_mesh(tuple(shape), tuple(axes),
                     devices if devices is not None else None)
