"""In-jit (SPMD) pipeline parallelism over a mesh axis.

parallel/pipeline.py runs each stage as its own jitted program on its
own device and lets XLA's async dispatch overlap them — host futures
ARE the schedule (the HPX dataflow-pipeline pattern, SURVEY.md §2.9 PP
row). This module is the compiler-side counterpart for when the
pipeline must live INSIDE one jitted multi-chip program so it composes
with dp/tp axes and rides ICI: stage parameters are stacked on a
leading axis sharded over the "pp" mesh axis, microbatches march
through a lax.scan, and the stage-to-stage handoff is one lax.ppermute
hop per step — the GPipe schedule expressed as data movement.

Schedule shape: with P stages and M microbatches the scan runs
T = M + P - 1 steps. At step t, stage 0 feeds microbatch min(t, M-1)
(clamped re-feeds are computed and discarded — every device runs the
same program), stage p processes what stage p-1 produced at t-1, and
stage P-1 emits microbatch t-(P-1) once t >= P-1. The fill/drain
bubble is the standard GPipe (P-1)/(M+P-1) fraction.

Differentiation: reverse-mode AD transposes the scan (reversed steps)
and each ppermute (inverse rotation), which IS the backward pipeline —
cotangents drain stage P-1 -> 0 in reverse schedule order. No
hand-written backward schedule exists or is needed; memory follows
GPipe (live activations for all in-flight microbatches), mitigated by
jax.checkpoint around the stage body (the caller's choice).

vma note (newer jax tracks varying-manual-axes): the scan carry's vma
set must match the stepped values'. `x0` and `acc0` must therefore be
pvaried over every axis the in-flight activation/accumulator varies on
(typically ("dp", "pp")) before calling pipeline_run — see
ops.attention._pvary and models/transformer.make_pipelined_train_step.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp

__all__ = ["pipeline_run", "pipeline_run_interleaved"]


def pipeline_run(axis: str, n_stages: int, n_microbatches: int,
                 stage_fn: Callable[[Any], Any],
                 feed: Callable[[jax.Array], Any],
                 collect: Callable[[Any, Any, jax.Array, jax.Array], Any],
                 acc0: Any, x0: Any) -> Any:
    """March n_microbatches through the pp stages; runs INSIDE an
    enclosing shard_map whose mesh carries `axis`.

    stage_fn(x) -> y        this device's stage (its slice of the
                            stacked layers), applied every step
    feed(t) -> x            microbatch t's entry activation (t is a
                            traced scalar already clamped to [0, M-1]);
                            only stage 0's result is consumed
    collect(acc, y, t_out, valid) -> acc
                            fold stage P-1's step output into the
                            accumulator; `valid` is a traced bool that
                            is True only on the last stage once real
                            output emerges (mask with it — do NOT
                            branch on it)
    acc0, x0                initial accumulator and in-flight
                            activation (zeros_like the stage output),
                            pvaried to the carry's vma (see module
                            docstring)
    """
    P, M = n_stages, n_microbatches
    idx = jax.lax.axis_index(axis)
    perm = [(i, i + 1) for i in range(P - 1)]

    def step(carry, t):
        x_recv, acc = carry
        x_first = feed(jnp.clip(t, 0, M - 1))
        x_in = jax.tree.map(
            lambda a, b: jnp.where(idx == 0, a, b), x_first, x_recv)
        y = stage_fn(x_in)
        t_out = jnp.clip(t - (P - 1), 0, M - 1)
        valid = jnp.logical_and(idx == P - 1, t >= P - 1)
        acc = collect(acc, y, t_out, valid)
        # stage p -> p+1; stage 0 receives zeros (it feeds itself),
        # stage P-1's send has no target (its output was collected)
        x_send = jax.tree.map(
            lambda a: jax.lax.ppermute(a, axis, perm), y)
        return (x_send, acc), None

    (_, acc), _ = jax.lax.scan(step, (x0, acc0), jnp.arange(M + P - 1))
    return acc


def pipeline_run_interleaved(axis: str, n_stages: int, n_virtual: int,
                             n_microbatches: int,
                             stage_fn: Callable[[jax.Array, Any], Any],
                             feed: Callable[[jax.Array], Any],
                             collect: Callable[[Any, Any, jax.Array,
                                                jax.Array], Any],
                             acc0: Any, x0_stack: Any) -> Any:
    """Interleaved (virtual-stage) pipeline, Megatron schedule: P*V
    stages assigned round-robin (stage s = v*P + d lives on device
    d = s % P as its chunk v = s // P). Each scan step a device
    computes ONE virtual chunk — 1/(P*V) of the layers — so the scan
    runs M*V + P - 1 steps of 1/V-slice cost: bubble fraction
    (P-1)/(M*V + P-1) versus plain GPipe's (P-1)/(M+P-1).

    The slot order per device (local slot u' = step - d) is Megatron's
    forward order — P microbatches through chunk 0, the same P through
    chunk 1, ... then the next P:

        chunk(u') = (u' % (P*V)) // P
        mb(u')    = (u' // (P*V)) * P + (u' % P)      [needs P | M]

    With every device skewed by d steps, a unit's producer always ran
    exactly one step earlier (also across the P-1 -> 0 chunk wrap), so
    one in-flight buffer per chunk suffices and the hop stays ONE
    static ppermute over the full ring. Chunk selection is per-device
    (a traced dynamic_index into the [V, ...] buffer and into the
    caller's layer groups) — NOT a lax.switch, which SPMD would
    execute V-fold, forfeiting the schedule's whole point.

    stage_fn(v, x) applies this device's chunk v (a traced scalar —
    dynamic_index your stacked layer groups with it). Backward is AD
    through the scan. x0_stack: zeros_like the [V, ...] buffer,
    pvaried to the carry's vma. collect sees stage P*V-1's outputs.
    """
    P, V, M = n_stages, n_virtual, n_microbatches
    if M % P:
        raise ValueError(
            f"interleaved schedule needs n_microbatches ({M}) divisible "
            f"by the stage count ({P})")
    PV = P * V
    idx = jax.lax.axis_index(axis)
    perm = [(i, (i + 1) % P) for i in range(P)]   # full ring, wraps

    def slot(u_local):
        v = (u_local % PV) // P
        m = (u_local // PV) * P + (u_local % P)
        return v, m

    def upd(xs, v, val):
        return jax.tree.map(
            lambda b, y: jax.lax.dynamic_update_index_in_dim(
                b, y, v, 0), xs, val)

    def step(carry, u):
        xs, acc = carry
        ul = u - idx                       # this device's local slot
        live = jnp.logical_and(ul >= 0, ul < M * V)
        v, m = slot(jnp.clip(ul, 0, M * V - 1))
        x_in = jax.tree.map(
            lambda b: jax.lax.dynamic_index_in_dim(b, v, 0,
                                                   keepdims=False), xs)
        x_feed = feed(jnp.clip(m, 0, M - 1))
        first = jnp.logical_and(idx == 0, v == 0)
        x_in = jax.tree.map(
            lambda f, x: jnp.where(first, f, x), x_feed, x_in)
        y = stage_fn(v, x_in)
        valid_out = live & (idx == P - 1) & (v == V - 1)
        acc = collect(acc, y, m, valid_out)
        recv = jax.tree.map(lambda a: jax.lax.ppermute(a, axis, perm), y)
        # fold the arrival: the sender (left ring neighbor) computed its
        # own slot at this same step; consumers use it NEXT step
        s_idx = (idx - 1) % P
        us = u - s_idx
        s_live = jnp.logical_and(us >= 0, us < M * V)
        sv, _sm = slot(jnp.clip(us, 0, M * V - 1))
        # same chunk for d>0; the P-1 -> 0 wrap advances the chunk
        rv = jnp.where(idx == 0, sv + 1, sv)
        arrival = s_live & (rv <= V - 1)
        xs_upd = upd(xs, jnp.clip(rv, 0, V - 1), recv)
        xs = jax.tree.map(
            lambda a, b: jnp.where(arrival, a, b), xs_upd, xs)
        return (xs, acc), None

    (_, acc), _ = jax.lax.scan(step, (x0_stack, acc0),
                               jnp.arange(M * V + P - 1))
    return acc
