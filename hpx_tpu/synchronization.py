"""Synchronization primitives.

Reference analog: libs/core/synchronization (hpx::mutex, spinlock,
condition_variable, counting_semaphore, sliding_semaphore, latch, barrier,
event, stop_token). HPX's versions *suspend the HPX thread* instead of
blocking the OS thread; in this runtime host tasks run on OS threads, so
Python's native primitives are the right substrate — the value added here
is (a) HPX's exact API shapes, (b) futures-returning variants that let the
dataflow layer wait without occupying a thread, and (c) the
suspend-while-holding-lock debug check (see core `held_locks`, analog of
HPX_WITH_VERIFY_LOCKS — SURVEY.md §5.2).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional

from .core.errors import DeadlockError, Error, HpxError
from .futures.future import Future, SharedState, make_ready_future

# ---------------------------------------------------------------------------
# VERIFY_LOCKS analog: registered locks held by the current thread. Waiting
# on a future while holding a registered lock aborts (the classic AMT
# deadlock HPX guards against with HPX_WITH_VERIFY_LOCKS).
_tls = threading.local()
_verify_locks = False


def enable_lock_verification(enable: bool = True) -> None:
    global _verify_locks
    _verify_locks = enable


def _held() -> List[Any]:
    lst = getattr(_tls, "held", None)
    if lst is None:
        lst = _tls.held = []
    return lst


def verify_no_locks_held(what: str = "wait") -> None:
    if _verify_locks and _held():
        raise DeadlockError(
            f"{what} while holding {len(_held())} registered lock(s) — "
            "suspension while holding a lock deadlocks the scheduler")


class Mutex:
    """hpx::mutex with lock-verification registration."""

    def __init__(self) -> None:
        self._lk = threading.Lock()

    def lock(self) -> None:
        self._lk.acquire()
        _held().append(self)

    def try_lock(self) -> bool:
        ok = self._lk.acquire(blocking=False)
        if ok:
            _held().append(self)
        return ok

    def unlock(self) -> None:
        _held().remove(self)
        self._lk.release()

    def __enter__(self) -> "Mutex":
        self.lock()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.unlock()


Spinlock = Mutex  # host-side: same substrate; kept for API parity


class SharedMutex:
    """hpx::shared_mutex: many readers / one writer, writer-preferring
    (waiting writers block NEW readers so writers can't starve), with
    lock-verification registration on both modes."""

    def __init__(self) -> None:
        self._cv = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0

    # -- exclusive ---------------------------------------------------------
    def lock(self) -> None:
        with self._cv:
            self._writers_waiting += 1
            try:
                # finally: an async exception (KeyboardInterrupt) in
                # the wait must not leave the waiting count raised —
                # readers gate on it, so a leak blocks them forever
                self._cv.wait_for(lambda: not self._writer
                                  and self._readers == 0)
                self._writer = True
            finally:
                self._writers_waiting -= 1
        _held().append(self)

    def try_lock(self) -> bool:
        with self._cv:
            if self._writer or self._readers:
                return False
            self._writer = True
        _held().append(self)
        return True

    def unlock(self) -> None:
        _held().remove(self)
        with self._cv:
            self._writer = False
            self._cv.notify_all()

    # -- shared ------------------------------------------------------------
    def lock_shared(self) -> None:
        with self._cv:
            self._cv.wait_for(lambda: not self._writer
                              and self._writers_waiting == 0)
            self._readers += 1
        _held().append(self)

    def try_lock_shared(self) -> bool:
        with self._cv:
            if self._writer or self._writers_waiting:
                return False
            self._readers += 1
        _held().append(self)
        return True

    def unlock_shared(self) -> None:
        _held().remove(self)
        with self._cv:
            self._readers -= 1
            if self._readers == 0:
                self._cv.notify_all()

    def __enter__(self) -> "SharedMutex":
        self.lock()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.unlock()

    class _SharedView:
        __slots__ = ("_m",)

        def __init__(self, m: "SharedMutex") -> None:
            self._m = m

        def __enter__(self):
            self._m.lock_shared()
            return self._m

        def __exit__(self, *exc: Any) -> None:
            self._m.unlock_shared()

    def shared(self) -> "_SharedView":
        """`with m.shared():` — std::shared_lock analog."""
        return SharedMutex._SharedView(self)


class ConditionVariable:
    def __init__(self) -> None:
        self._cv = threading.Condition()

    def wait(self, pred: Optional[Callable[[], bool]] = None,
             timeout: Optional[float] = None) -> bool:
        verify_no_locks_held("condition_variable::wait")
        with self._cv:
            if pred is None:
                return self._cv.wait(timeout)
            return self._cv.wait_for(pred, timeout)

    def notify_one(self) -> None:
        with self._cv:
            self._cv.notify()

    def notify_all(self) -> None:
        with self._cv:
            self._cv.notify_all()


class Latch:
    """hpx::latch: single-use countdown; wait via block or future."""

    def __init__(self, count: int) -> None:
        if count < 0:
            raise HpxError(Error.bad_parameter, "latch count must be >= 0")
        self._lock = threading.Lock()
        self._count = count
        self._state = SharedState()
        if count == 0:
            self._state.set_value(None)

    def count_down(self, n: int = 1) -> None:
        with self._lock:
            if self._count < n:
                raise HpxError(Error.invalid_status, "latch over-decremented")
            self._count -= n
            fire = self._count == 0
        if fire:
            self._state.set_value(None)

    def try_wait(self) -> bool:
        return self._state.is_ready()

    def wait(self, timeout: Optional[float] = None) -> bool:
        verify_no_locks_held("latch::wait")
        return self._state.wait(timeout)

    def arrive_and_wait(self, n: int = 1,
                        timeout: Optional[float] = None) -> bool:
        self.count_down(n)
        return self.wait(timeout)

    def get_future(self) -> Future[None]:
        return Future(self._state)


class Barrier:
    """hpx::barrier<>: cyclic; arrive_and_wait, with completion callback."""

    def __init__(self, count: int,
                 on_completion: Optional[Callable[[], None]] = None) -> None:
        if count <= 0:
            raise HpxError(Error.bad_parameter, "barrier count must be > 0")
        self._count = count
        self._on_completion = on_completion
        self._lock = threading.Lock()
        self._arrived = 0
        self._state = SharedState()

    def arrive(self, n: int = 1) -> Future[None]:
        """Arrive without waiting; returned future fires on phase done."""
        with self._lock:
            st = self._state
            self._arrived += n
            fire = self._arrived >= self._count
            if fire:
                # open next phase before releasing waiters
                self._arrived = 0
                self._state = SharedState()
        if fire:
            if self._on_completion is not None:
                self._on_completion()
            st.set_value(None)
        return Future(st)

    def arrive_and_wait(self, timeout: Optional[float] = None) -> bool:
        verify_no_locks_held("barrier::arrive_and_wait")
        return self.arrive().wait(timeout)

    def arrive_and_drop(self) -> None:
        with self._lock:
            self._count -= 1
            st = self._state
            fire = self._arrived >= self._count and self._count > 0
            if fire:
                self._arrived = 0
                self._state = SharedState()
        if fire:
            if self._on_completion is not None:
                self._on_completion()
            st.set_value(None)


class CountingSemaphore:
    """hpx::counting_semaphore."""

    def __init__(self, value: int = 0) -> None:
        self._sem = threading.Semaphore(value)

    def acquire(self, timeout: Optional[float] = None) -> bool:
        verify_no_locks_held("semaphore::acquire")
        return self._sem.acquire(timeout=timeout)

    def try_acquire(self) -> bool:
        return self._sem.acquire(blocking=False)

    def release(self, n: int = 1) -> None:
        self._sem.release(n)


class SlidingSemaphore:
    """hpx::sliding_semaphore: bounds the distance between a monotonically
    growing lower and upper value (used to throttle in-flight pipeline
    stages — e.g. how far ahead the host may run dispatching device steps).

    wait(t): block until t - max_difference <= lower. signal(l): advance.
    """

    def __init__(self, max_difference: int, lower: int = 0) -> None:
        self._max_diff = max_difference
        self._lower = lower
        self._cv = threading.Condition()

    def wait(self, upper: int, timeout: Optional[float] = None) -> bool:
        verify_no_locks_held("sliding_semaphore::wait")
        with self._cv:
            return self._cv.wait_for(
                lambda: upper - self._max_diff <= self._lower, timeout)

    def try_wait(self, upper: int) -> bool:
        with self._cv:
            return upper - self._max_diff <= self._lower

    def signal(self, lower: int) -> None:
        with self._cv:
            self._lower = max(self._lower, lower)
            self._cv.notify_all()


class Event:
    """hpx::lcos::local::event: manual-reset gate."""

    def __init__(self) -> None:
        self._ev = threading.Event()

    def occurred(self) -> bool:
        return self._ev.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        verify_no_locks_held("event::wait")
        return self._ev.wait(timeout)

    def set(self) -> None:
        self._ev.set()

    def reset(self) -> None:
        self._ev.clear()


class StopSource:
    """std::stop_source/std::stop_token analog (hpx::stop_token)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._stopped = False
        self._callbacks: List[Callable[[], None]] = []

    def request_stop(self) -> bool:
        with self._lock:
            if self._stopped:
                return False
            self._stopped = True
            cbs = list(self._callbacks)
            self._callbacks.clear()
        for cb in cbs:
            cb()
        return True

    def stop_requested(self) -> bool:
        return self._stopped

    def get_token(self) -> "StopToken":
        return StopToken(self)


class StopToken:
    def __init__(self, source: StopSource) -> None:
        self._source = source

    def stop_requested(self) -> bool:
        return self._source.stop_requested()

    def on_stop(self, cb: Callable[[], None]) -> None:
        src = self._source
        with src._lock:
            if not src._stopped:
                src._callbacks.append(cb)
                return
        cb()
