from .stencil import heat_step, multistep, pallas_multistep, xla_multistep  # noqa: F401
