from .stencil import heat_step, multistep, pallas_multistep, xla_multistep  # noqa: F401
from .attention import (  # noqa: F401
    auto_attention,
    blockwise_attention,
    reference_attention,
    ring_attention,
    ring_attention_sharded,
    ulysses_attention,
)
from .attention_pallas import flash_attention  # noqa: F401
from .paged_attention import (  # noqa: F401
    gather_block_kv,
    paged_decode_attention,
    scatter_blocks,
    scatter_token,
)
