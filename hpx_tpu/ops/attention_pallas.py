"""Pallas flash-attention kernel (single chip).

The MXU-resident inner loop for ops/attention.py: Q/K/V stream through
VMEM in (block_q × block_k) tiles over a sequential TPU grid; the
online-softmax state (acc, m, l) lives in VMEM scratch and carries
across the K dimension of the grid (TPU grids execute in order, so the
innermost axis is the flash loop). Causal blocks below the diagonal are
skipped entirely (`pl.when`), not just masked — ~2× fewer tiles.

Layout: [B, S, N, H] public shape; kernel works on [B*N, S, H] with the
(S, H) tiles as MXU operands (H = 64/128 hits the 128-lane layout).

`flash_attention` falls back to interpret mode off-TPU so the same
kernel is testable on the CPU mesh (pallas interpret semantics).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention", "flash_attention_chunk"]


def _sds(shape, dtype, *operands):
    """ShapeDtypeStruct whose varying-mesh-axes type is the union of the
    operands' — required when a pallas_call runs INSIDE a vma-checked
    shard_map (the kernel output varies over whatever its inputs do)."""
    try:
        vma = frozenset().union(*(jax.typeof(x).vma for x in operands))
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except (AttributeError, TypeError):
        return jax.ShapeDtypeStruct(shape, dtype)

_NEG_INF = -1e30     # large-negative instead of -inf: exp() stays exact,
                     # and (m_prev - m_new) never produces inf - inf


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                  block_q: int, block_k: int, nk: int, causal: bool,
                  scale: float, seq_q: int, seq_k: int):
    iq = pl.program_id(1)
    ik = pl.program_id(2)

    # bottom-right causal alignment (matches reference_attention /
    # blockwise_attention): query qi attends keys kj <= qi + (sk - sq),
    # so a cross-attention suffix lines up with the END of the keys.
    off = seq_k - seq_q

    @pl.when(ik == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # causal: the whole tile is masked iff its smallest k position
    # exceeds the largest (offset-adjusted) q position
    if causal:
        live = ik * block_k <= iq * block_q + block_q - 1 + off
    else:
        live = True

    @pl.when(live)
    def _compute():
        # keep q/k/v in their storage dtype for the dots: bf16 operands
        # run the MXU at full rate; preferred_element_type=f32 keeps the
        # ACCUMULATION in fp32 (the flash-attention numerics contract).
        # The scale is applied to the f32 scores, not the bf16 operands.
        q = q_ref[0]                               # (block_q, H)
        k = k_ref[0]                               # (block_k, H)
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

        # in-tile masks: sequence padding tail + causal diagonal
        kpos = ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        mask = kpos < seq_k
        if causal:
            qpos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            mask = jnp.logical_and(mask, kpos <= qpos + off)
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_ref[:, :1]                      # (block_q, 1)
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        p = jnp.where(mask, p, 0.0)                # masked lanes: exact 0
        l_ref[:] = jnp.broadcast_to(corr * l_prev + p.sum(
            axis=1, keepdims=True), l_ref.shape)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        # second matmul in the storage dtype too (p cast bf16 when v is
        # bf16 — standard flash practice), still accumulated in fp32
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p.astype(v.dtype) if v.dtype == jnp.bfloat16 else p, v,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ik == nk - 1)
    def _finish():
        l = l_ref[:, :1]
        den = jnp.where(l > 0, l, 1.0)
        o_ref[0] = (acc_ref[:] / den).astype(o_ref.dtype)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = False, block_q: int = 1024,
                    block_k: int = 1024,
                    interpret: Optional[bool] = None) -> jax.Array:
    """[B, S, N, H] flash attention as one pallas_call per device.

    S is padded to the block size internally; H should be a multiple of
    the 128-lane layout's tile for best MXU utilization (64/128).
    """
    b, sq, n, h = q.shape
    sk = k.shape[1]
    if interpret is None:
        interpret = jax.default_backend() == "cpu"

    block_q = min(block_q, max(sq, 8))
    block_k = min(block_k, max(sk, 8))
    pq = -sq % block_q
    pk = -sk % block_k

    qt = jnp.moveaxis(q, 2, 1).reshape(b * n, sq, h)
    kt = jnp.moveaxis(k, 2, 1).reshape(b * n, sk, h)
    vt = jnp.moveaxis(v, 2, 1).reshape(b * n, sk, h)
    if pq:
        qt = jnp.pad(qt, ((0, 0), (0, pq), (0, 0)))
    if pk:
        kt = jnp.pad(kt, ((0, 0), (0, pk), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, pk), (0, 0)))
    nq = qt.shape[1] // block_q
    nk = kt.shape[1] // block_k

    kernel = functools.partial(
        _flash_kernel, block_q=block_q, block_k=block_k, nk=nk,
        causal=causal, scale=1.0 / math.sqrt(h), seq_q=sq, seq_k=sk)

    out = pl.pallas_call(
        kernel,
        grid=(b * n, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, h), lambda bn, iq, ik: (bn, iq, 0)),
            pl.BlockSpec((1, block_k, h), lambda bn, iq, ik: (bn, ik, 0)),
            pl.BlockSpec((1, block_k, h), lambda bn, iq, ik: (bn, ik, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, h),
                               lambda bn, iq, ik: (bn, iq, 0)),
        out_shape=_sds((b * n, nq * block_q, h), q.dtype, q, k, v),
        scratch_shapes=[
            pltpu.VMEM((block_q, h), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qt, kt, vt)

    out = out[:, :sq].reshape(b, n, sq, h)
    return jnp.moveaxis(out, 1, 2)


# ---------------------------------------------------------------------------
# chunked variant with carry I/O — the ring-attention inner kernel
# ---------------------------------------------------------------------------

def _flash_chunk_kernel(d_ref, q_ref, k_ref, v_ref, acc_in, m_in, l_in,
                        acc_out, m_out, l_out, acc_s, m_s, l_s, *,
                        block_q: int, block_k: int, nk: int,
                        causal: bool, scale: float):
    """One K/V CHUNK folded into an online-softmax carry.

    Same tile loop as _flash_kernel, but the (acc, m, l) state arrives
    as inputs and leaves UNNORMALIZED as outputs, so a ring step
    (ops/attention.py ring_attention_sharded) can fold one rotating
    chunk per call. `d_ref` (SMEM) holds the TRACED relative offset
    d = q_global_start - k_global_start: causal masking inside the
    kernel is kpos <= qpos + d, which stays correct whichever ring step
    the chunk arrives on. m/l travel in a 128-lane replicated layout
    ([bn, s, 128]) to match the VMEM scratch tiling.
    """
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    d = d_ref[0]

    @pl.when(ik == 0)
    def _load_carry():
        acc_s[:] = acc_in[0]
        m_s[:] = m_in[0]
        l_s[:] = l_in[0]

    if causal:
        live = ik * block_k <= iq * block_q + block_q - 1 + d
    else:
        live = True

    @pl.when(live)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

        if causal:
            kpos = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            qpos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            mask = kpos <= qpos + d
            s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_s[:, :1]
        l_prev = l_s[:, :1]
        m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        if causal:
            p = jnp.where(mask, p, 0.0)
        l_s[:] = jnp.broadcast_to(corr * l_prev + p.sum(
            axis=1, keepdims=True), l_s.shape)
        m_s[:] = jnp.broadcast_to(m_new, m_s.shape)
        acc_s[:] = acc_s[:] * corr + jax.lax.dot_general(
            p.astype(v.dtype) if v.dtype == jnp.bfloat16 else p, v,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ik == nk - 1)
    def _store_carry():
        acc_out[0] = acc_s[:]
        m_out[0] = m_s[:]
        l_out[0] = l_s[:]


def flash_attention_chunk(q, k, v, acc, m, l, d,
                          causal: bool = False, block_q: int = 1024,
                          block_k: int = 1024,
                          interpret: Optional[bool] = None):
    """Fold one K/V chunk into an online-softmax carry (pallas).

    Layouts (kernel-native, NO [B,S,N,H] public shape here — the ring
    transposes once outside its scan): q [bn, sq, h]; k/v [bn, sk, h];
    acc [bn, sq, h] f32; m/l [bn, sq, 128] f32 (lane-replicated).
    `d` is a traced int32 scalar: q_global_start - k_global_start.
    Returns updated (acc, m, l), unnormalized. Finalize with
    acc / max(l, eps) outside (ops/attention._finish agrees).

    sq and sk must be multiples of the (clamped) block sizes — ring
    chunks are equal by construction.
    """
    import math as _math
    bn, sq, h = q.shape
    sk = k.shape[1]
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if sq % block_q or sk % block_k:
        raise ValueError(
            f"chunk sizes must divide blocks: sq={sq}/{block_q}, "
            f"sk={sk}/{block_k}")
    nq = sq // block_q
    nk = sk // block_k

    kernel = functools.partial(
        _flash_chunk_kernel, block_q=block_q, block_k=block_k, nk=nk,
        causal=causal, scale=1.0 / _math.sqrt(h))

    f32 = jnp.float32
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(bn, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, h), lambda bn_, iq, ik, *_: (bn_, iq, 0)),
            pl.BlockSpec((1, block_k, h), lambda bn_, iq, ik, *_: (bn_, ik, 0)),
            pl.BlockSpec((1, block_k, h), lambda bn_, iq, ik, *_: (bn_, ik, 0)),
            pl.BlockSpec((1, block_q, h), lambda bn_, iq, ik, *_: (bn_, iq, 0)),
            pl.BlockSpec((1, block_q, 128),
                         lambda bn_, iq, ik, *_: (bn_, iq, 0)),
            pl.BlockSpec((1, block_q, 128),
                         lambda bn_, iq, ik, *_: (bn_, iq, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, h), lambda bn_, iq, ik, *_: (bn_, iq, 0)),
            pl.BlockSpec((1, block_q, 128),
                         lambda bn_, iq, ik, *_: (bn_, iq, 0)),
            pl.BlockSpec((1, block_q, 128),
                         lambda bn_, iq, ik, *_: (bn_, iq, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, h), f32),
            pltpu.VMEM((block_q, 128), f32),
            pltpu.VMEM((block_q, 128), f32),
        ],
    )

    acc2, m2, l2 = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            _sds((bn, sq, h), f32, q, k, v, acc, m, l),
            _sds((bn, sq, 128), f32, q, k, v, acc, m, l),
            _sds((bn, sq, 128), f32, q, k, v, acc, m, l),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(jnp.asarray([d], jnp.int32).reshape(1), q, k, v, acc, m, l)
    return acc2, m2, l2
