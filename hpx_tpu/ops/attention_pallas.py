"""Pallas flash-attention kernels (single chip): forward AND backward.

The MXU-resident inner loop for ops/attention.py: Q/K/V stream through
VMEM in (block_q × block_k) tiles over a sequential TPU grid; the
online-softmax state (acc, m, l) lives in VMEM scratch and carries
across the K dimension of the grid (TPU grids execute in order, so the
innermost axis is the flash loop). Causal blocks below the diagonal are
skipped entirely (`pl.when`), not just masked — ~2× fewer tiles.

Layout: [B, S, N, H] public shape; kernel works on [B*N, S, H] with the
(S, H) tiles as MXU operands (H = 64/128 hits the 128-lane layout).

`flash_attention` carries a `jax.custom_vjp`: the forward saves the
per-row logsumexp L = m + log(l) (lane-replicated, the same layout the
scratch uses), and the backward is the standard two-pass flash
backward — one kernel accumulates dQ (grid inner axis walks K blocks),
a second accumulates dK/dV (inner axis walks Q blocks), both
recomputing p = exp(s − L) tile-by-tile so nothing O(S²) is ever
materialized. Both backward kernels take the q/k global offset `d` as
a scalar-prefetch operand, so the SAME kernels serve the ring-attention
backward (ops/attention._ring_flash), where d is traced per ring step.

`flash_attention` falls back to interpret mode off-TPU so the same
kernels are testable on the CPU mesh (pallas interpret semantics).
"""

from __future__ import annotations

import functools
import json
import math
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams across 0.4/0.5;
# accept either so the kernels run on both
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

__all__ = ["flash_attention", "flash_attention_chunk",
           "flash_attention_bwd", "fused_paged_attention",
           "fused_paged_online_attention",
           "paged_online_scratch_shapes",
           "resolve_blocks", "resolve_paged_block",
           "resolve_paged_block_src"]


# ---------------------------------------------------------------------------
# forward block-size selection
# ---------------------------------------------------------------------------
# Tile shape is THE forward-MFU lever at short S (causal diagonal tiles
# are half-masked: with 1024^2 blocks at S=4096 a fifth of the MXU work
# is wasted; smaller block_k trims the diagonal waste but adds per-tile
# loop overhead — the right point is measured, not derived). Resolution
# order: explicit arg > HPX_FLASH_BLOCK_Q/K env > measured table
# (benchmarks/flash_tune.py writes flash_blocks.json next to this file
# after sweeping on real hardware) > 1024x1024 default.

_BLOCKS_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "flash_blocks.json")
_blocks_table: Optional[dict] = None


def _load_blocks_table() -> dict:
    global _blocks_table
    if _blocks_table is None:
        try:
            with open(_BLOCKS_FILE) as f:
                _blocks_table = {k: tuple(v)
                                 for k, v in json.load(f).items()}
        except (OSError, ValueError):
            _blocks_table = {}
    return _blocks_table


def resolve_blocks(seq_q: int, seq_k: int,
                   causal: bool) -> Tuple[int, int]:
    """The (block_q, block_k) the forward kernel will use for this
    shape class when the caller doesn't pass blocks explicitly."""
    table = _load_blocks_table()
    bq, bk = table.get(f"{seq_q}x{seq_k}x{int(causal)}", (1024, 1024))
    # env overrides are PER-DIMENSION: the unset one keeps the
    # table/default value rather than snapping back to 1024
    env_q = os.environ.get("HPX_FLASH_BLOCK_Q")
    env_k = os.environ.get("HPX_FLASH_BLOCK_K")
    if env_q:
        bq = int(env_q)
    if env_k:
        bk = int(env_k)
    return bq, bk


def _sds(shape, dtype, *operands):
    """ShapeDtypeStruct whose varying-mesh-axes type is the union of the
    operands' — required when a pallas_call runs INSIDE a vma-checked
    shard_map (the kernel output varies over whatever its inputs do)."""
    try:
        vma = frozenset().union(*(jax.typeof(x).vma for x in operands))
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except (AttributeError, TypeError):
        return jax.ShapeDtypeStruct(shape, dtype)

_NEG_INF = -1e30     # large-negative instead of -inf: exp() stays exact,
                     # and (m_prev - m_new) never produces inf - inf


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *rest,
                  block_q: int, block_k: int, nk: int, causal: bool,
                  scale: float, seq_q: int, seq_k: int,
                  save_res: bool = False):
    if save_res:
        lse_ref, acc_ref, m_ref, l_ref = rest
    else:
        acc_ref, m_ref, l_ref = rest
    iq = pl.program_id(1)
    ik = pl.program_id(2)

    # bottom-right causal alignment (matches reference_attention /
    # blockwise_attention): query qi attends keys kj <= qi + (sk - sq),
    # so a cross-attention suffix lines up with the END of the keys.
    off = seq_k - seq_q

    @pl.when(ik == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # causal: the whole tile is masked iff its smallest k position
    # exceeds the largest (offset-adjusted) q position
    if causal:
        live = ik * block_k <= iq * block_q + block_q - 1 + off
    else:
        live = True

    @pl.when(live)
    def _compute():
        # keep q/k/v in their storage dtype for the dots: bf16 operands
        # run the MXU at full rate; preferred_element_type=f32 keeps the
        # ACCUMULATION in fp32 (the flash-attention numerics contract).
        # The scale is applied to the f32 scores, not the bf16 operands.
        q = q_ref[0]                               # (block_q, H)
        k = k_ref[0]                               # (block_k, H)
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

        # in-tile masks: sequence padding tail + causal diagonal
        kpos = ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        mask = kpos < seq_k
        if causal:
            qpos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            mask = jnp.logical_and(mask, kpos <= qpos + off)
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_ref[:, :1]                      # (block_q, 1)
        m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(mask, p, 0.0)                # masked lanes: exact 0

        # delayed rescaling: the (corr = exp(m_prev - m_new)) multiply
        # of acc and l is an exact no-op on every tile where the running
        # max didn't move (corr == exp(0) == 1) — common once the max
        # stabilizes along the k walk. Rescale CONDITIONALLY (one scalar
        # reduction gates a (block_q, H) + (block_q, 128) VPU multiply),
        # then accumulate unconditionally.
        @pl.when(jnp.logical_not((m_new == m_prev).all()))
        def _rescale():
            corr = jnp.exp(m_prev - m_new)
            acc_ref[:] = acc_ref[:] * corr
            l_ref[:] = l_ref[:] * corr

        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = l_ref[:] + jnp.broadcast_to(
            p.sum(axis=1, keepdims=True), l_ref.shape)
        # second matmul in the storage dtype too (p cast bf16 when v is
        # bf16 — standard flash practice), still accumulated in fp32
        acc_ref[:] = acc_ref[:] + jax.lax.dot_general(
            p.astype(v.dtype) if v.dtype == jnp.bfloat16 else p, v,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ik == nk - 1)
    def _finish():
        l = l_ref[:, :1]
        den = jnp.where(l > 0, l, 1.0)
        o_ref[0] = (acc_ref[:] / den).astype(o_ref.dtype)
        if save_res:
            # logsumexp per row, lane-replicated; fully-masked rows
            # (l == 0: sequence padding, causal rows with no keys) get
            # L = 0 so the backward's exp(s - L) stays finite — their
            # contributions vanish through masks / zero cotangents.
            lf = l_ref[:]
            safe = jnp.where(lf > 0, lf, 1.0)
            lse_ref[0] = jnp.where(lf > 0, m_ref[:] + jnp.log(safe), 0.0)


def _kernel_layout(x: jax.Array) -> jax.Array:
    """[B, S, N, H] -> [B*N, S, H] (the MXU-operand layout)."""
    b, s, n, h = x.shape
    return jnp.moveaxis(x, 2, 1).reshape(b * n, s, h)


def _pad_seq(x: jax.Array, pad: int) -> jax.Array:
    return jnp.pad(x, ((0, 0), (0, pad), (0, 0))) if pad else x


def _kv_row_map(q_heads: int, kv_heads: int):
    """Grid-row remap for grouped-query attention: q row bn = bi*Nq + ni
    reads K/V row bi*Nkv + ni // (Nq/Nkv). Identity when heads match —
    GQA costs ONLY this index arithmetic, never a materialized repeat."""
    if q_heads == kv_heads:
        return lambda bn: bn
    group = q_heads // kv_heads
    return lambda bn: (bn // q_heads) * kv_heads + (bn % q_heads) // group


def _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret,
                    save_res):
    b, sq, n, h = q.shape
    sk = k.shape[1]
    nkv = k.shape[2]
    if v.shape[2] != nkv:
        raise ValueError(f"k heads ({nkv}) != v heads ({v.shape[2]})")
    if n % nkv:
        raise ValueError(f"q heads ({n}) not a multiple of kv heads "
                         f"({nkv})")
    kv_of = _kv_row_map(n, nkv)

    block_q = min(block_q, max(sq, 8))
    block_k = min(block_k, max(sk, 8))
    pq = -sq % block_q
    pk = -sk % block_k

    qt = _pad_seq(_kernel_layout(q), pq)
    kt = _pad_seq(_kernel_layout(k), pk)
    vt = _pad_seq(_kernel_layout(v), pk)
    nq = qt.shape[1] // block_q
    nk = kt.shape[1] // block_k

    kernel = functools.partial(
        _flash_kernel, block_q=block_q, block_k=block_k, nk=nk,
        causal=causal, scale=1.0 / math.sqrt(h), seq_q=sq, seq_k=sk,
        save_res=save_res)

    out_specs = [pl.BlockSpec((1, block_q, h),
                              lambda bn, iq, ik: (bn, iq, 0))]
    out_shape = [_sds((b * n, nq * block_q, h), q.dtype, q, k, v)]
    if save_res:
        out_specs.append(pl.BlockSpec((1, block_q, 128),
                                      lambda bn, iq, ik: (bn, iq, 0)))
        out_shape.append(
            _sds((b * n, nq * block_q, 128), jnp.float32, q, k, v))

    res = pl.pallas_call(
        kernel,
        grid=(b * n, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, h), lambda bn, iq, ik: (bn, iq, 0)),
            pl.BlockSpec((1, block_k, h),
                         lambda bn, iq, ik: (kv_of(bn), ik, 0)),
            pl.BlockSpec((1, block_k, h),
                         lambda bn, iq, ik: (kv_of(bn), ik, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, h), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qt, kt, vt)

    out = res[0][:, :sq].reshape(b, n, sq, h)
    out = jnp.moveaxis(out, 1, 2)
    if save_res:
        return out, res[1][:, :sq]          # L in kernel layout
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention(q, k, v, causal, block_q, block_k, interpret):
    return _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret,
                           save_res=False)


def _fa_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _flash_fwd_impl(q, k, v, causal, block_q, block_k,
                               interpret, save_res=True)
    # keep ONE lane of the lane-replicated logsumexp as the residual
    # (128x smaller held fwd->bwd); _fa_bwd re-broadcasts
    return out, (q, k, v, out, lse[:, :, :1])


def _fa_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, o, lse = res
    b, sq, n, h = q.shape
    sk = k.shape[1]
    nkv = k.shape[2]
    # backward tiles keep four (bq, bk) f32 intermediates live in VMEM
    # (s, p, dp, ds) — cap blocks at 512 so 512x512x4B x4 = 4 MB fits
    bq = min(block_q, 512, max(sq, 8))
    bk = min(block_k, 512, max(sk, 8))
    pq = -sq % bq
    pk = -sk % bk

    qt = _pad_seq(_kernel_layout(q), pq)
    dot_ = _pad_seq(_kernel_layout(g.astype(q.dtype)), pq)
    ot = _pad_seq(_kernel_layout(o), pq)
    kt = _pad_seq(_kernel_layout(k), pk)
    vt = _pad_seq(_kernel_layout(v), pk)
    lp = jnp.pad(lse, ((0, 0), (0, pq), (0, 0))) if pq else lse
    delta128, lse128 = bwd_prep(dot_, ot, lp)

    dq, dk, dv = flash_attention_bwd(
        qt, kt, vt, dot_, delta128, lse128, sk - sq, causal=causal,
        block_q=bq, block_k=bk, interpret=interpret, seq_k=sk,
        q_heads=n, kv_heads=nkv)

    def back(x, s, nh, dtype):
        return jnp.moveaxis(
            x[:, :s].reshape(b, nh, s, h), 1, 2).astype(dtype)

    return (back(dq, sq, n, q.dtype), back(dk, sk, nkv, k.dtype),
            back(dv, sk, nkv, v.dtype))


_flash_attention.defvjp(_fa_fwd, _fa_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = False,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None) -> jax.Array:
    """[B, S, N, H] flash attention as one pallas_call per device.

    block_q/block_k default to resolve_blocks' per-shape-class choice
    (env override / measured autotune table / 1024). S is padded to the
    block size internally; H should be a multiple of the 128-lane
    layout's tile for best MXU utilization (64/128).
    Differentiable: jax.custom_vjp routes reverse-mode through the
    pallas backward kernels (flash_attention_bwd).

    GQA/MQA: k/v may carry FEWER heads than q (N % Nkv == 0). K/V tiles
    are shared across each q-head group via BlockSpec index remapping —
    no materialized repeat, so the serving-standard grouped layouts get
    the full KV-bandwidth saving; backward group-sums dK/dV.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if block_q is None or block_k is None:
        rq, rk = resolve_blocks(q.shape[1], k.shape[1], causal)
        block_q = rq if block_q is None else block_q
        block_k = rk if block_k is None else block_k
    return _flash_attention(q, k, v, causal, block_q, block_k, interpret)


def bwd_prep(dot_, ot, lse1):
    """flash_attention_bwd's input contract, in one place: delta =
    rowsum(do * o) in one fused XLA pass (the kernels never touch o),
    and lse/delta broadcast to the [bn, sq, 128] lane-replicated f32
    layout the kernels' (1, block_q, 128) tiles expect. `lse1` is the
    single-lane [bn, sq, 1] residual the forward saves."""
    delta = (dot_.astype(jnp.float32) * ot.astype(jnp.float32)
             ).sum(axis=-1, keepdims=True)
    shape = (dot_.shape[0], dot_.shape[1], 128)
    return (jnp.broadcast_to(delta, shape),
            jnp.broadcast_to(lse1, shape))


# ---------------------------------------------------------------------------
# backward kernels — standard two-pass flash backward
# ---------------------------------------------------------------------------
#
# Math (s = scale * q k^T; p = softmax rows; o = p v; L = row logsumexp):
#   p     = exp(s - L)                      (recomputed per tile, stable:
#                                            s - L <= -log l <= 0)
#   delta = rowsum(do * o)                  (= p . dp per row)
#   ds    = p * (dp - delta) * scale,  dp = do v^T
#   dq    = ds k        dk = ds^T q        dv = p^T do
#
# Both kernels take the q/k global offset d (causal: kpos <= qpos + d)
# as scalar prefetch so the ring backward can trace it per step.

def _flash_bwd_dq_kernel(d_ref, q_ref, k_ref, v_ref, do_ref,
                         delta_ref, lse_ref, dq_ref, dq_s, *,
                         block_q: int, block_k: int, nk: int,
                         causal: bool, scale: float, seq_k: int):
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    d = d_ref[0]

    @pl.when(ik == 0)
    def _init():
        dq_s[:] = jnp.zeros_like(dq_s)

    if causal:
        live = ik * block_k <= iq * block_q + block_q - 1 + d
    else:
        live = True

    @pl.when(live)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        kpos = ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        mask = kpos < seq_k
        if causal:
            qpos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            mask = jnp.logical_and(mask, kpos <= qpos + d)
        p = jnp.exp(s - lse_ref[0][:, :1])
        p = jnp.where(mask, p, 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0][:, :1]) * scale
        dq_s[:] = dq_s[:] + jax.lax.dot_general(
            ds.astype(k.dtype) if k.dtype == jnp.bfloat16 else ds, k,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ik == nk - 1)
    def _store():
        dq_ref[0] = dq_s[:]


def _flash_bwd_dkv_kernel(d_ref, q_ref, k_ref, v_ref, do_ref,
                          delta_ref, lse_ref, dk_ref, dv_ref, dk_s,
                          dv_s, *, block_q: int, block_k: int, nq: int,
                          causal: bool, scale: float, seq_k: int):
    ik = pl.program_id(1)
    iq = pl.program_id(2)
    d = d_ref[0]

    @pl.when(iq == 0)
    def _init():
        dk_s[:] = jnp.zeros_like(dk_s)
        dv_s[:] = jnp.zeros_like(dv_s)

    if causal:
        live = ik * block_k <= iq * block_q + block_q - 1 + d
    else:
        live = True

    @pl.when(live)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        kpos = ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        mask = kpos < seq_k
        if causal:
            qpos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            mask = jnp.logical_and(mask, kpos <= qpos + d)
        p = jnp.exp(s - lse_ref[0][:, :1])
        p = jnp.where(mask, p, 0.0)
        # dv += p^T do  (contract the q dimension)
        dv_s[:] = dv_s[:] + jax.lax.dot_general(
            p.astype(do.dtype) if do.dtype == jnp.bfloat16 else p, do,
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0][:, :1]) * scale
        dk_s[:] = dk_s[:] + jax.lax.dot_general(
            ds.astype(q.dtype) if q.dtype == jnp.bfloat16 else ds, q,
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(iq == nq - 1)
    def _store():
        dk_ref[0] = dk_s[:]
        dv_ref[0] = dv_s[:]


def flash_attention_bwd(q, k, v, do, delta, lse, d,
                        causal: bool = False, block_q: int = 512,
                        block_k: int = 512,
                        interpret: Optional[bool] = None,
                        seq_k: Optional[int] = None,
                        q_heads: int = 1, kv_heads: int = 1):
    """Flash-attention backward in kernel-native layout.

    q/do: [bn, sq, h]; k/v: [bn_kv, sk, h]; delta/lse: [bn, sq, 128]
    f32, lane-replicated — lse is the forward's row logsumexp, delta is
    rowsum(do * o) precomputed once by the caller (one fused XLA pass;
    the kernels never touch o). d: int32 scalar (traced OK) =
    q_global_start - k_global_start, the causal offset. sq/sk must be
    multiples of the block sizes (callers pad; zero-padded do rows and
    k/v rows contribute exact zeros).

    GQA: with q_heads > kv_heads (q rows bn = b*q_heads, k/v rows
    bn_kv = b*kv_heads), K/V tiles are index-remapped per q row and the
    per-q-head dK/dV partials are group-summed before returning.

    Returns (dq [bn,sq,h], dk [bn_kv,sk,h], dv [bn_kv,sk,h]) — float32,
    so ring steps can accumulate partials without bf16 round-off.
    """
    bn, sq, h = q.shape
    sk = k.shape[1]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if seq_k is None:
        seq_k = sk
    if sq % block_q or sk % block_k:
        raise ValueError(
            f"bwd seq not block-aligned: sq={sq}/{block_q}, "
            f"sk={sk}/{block_k}")
    nq = sq // block_q
    nk = sk // block_k
    scale = 1.0 / math.sqrt(h)
    f32 = jnp.float32
    darr = jnp.asarray([d], jnp.int32).reshape(1)
    kv_of = _kv_row_map(q_heads, kv_heads)

    q_at_iq = pl.BlockSpec((1, block_q, h),
                           lambda bn_, iq, ik, *_: (bn_, iq, 0))
    k_at_ik = pl.BlockSpec((1, block_k, h),
                           lambda bn_, iq, ik, *_: (kv_of(bn_), ik, 0))
    l_at_iq = pl.BlockSpec((1, block_q, 128),
                           lambda bn_, iq, ik, *_: (bn_, iq, 0))

    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel, block_q=block_q, block_k=block_k,
            nk=nk, causal=causal, scale=scale, seq_k=seq_k),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(bn, nq, nk),
            in_specs=[q_at_iq, k_at_ik, k_at_ik, q_at_iq, l_at_iq,
                      l_at_iq],
            out_specs=[q_at_iq],
            scratch_shapes=[
                pltpu.VMEM((block_q, h), f32),
            ],
        ),
        out_shape=[_sds((bn, sq, h), f32, q, k, v, do, delta, lse)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(darr, q, k, v, do, delta, lse)[0]

    # dk/dv grid: k blocks on the parallel axis, q blocks innermost.
    # Outputs are PER Q ROW (bn) — with GQA several q rows share a K/V
    # row, and overlapping output maps across a parallel grid axis
    # would race; the group-sum below folds them to per-KV-row grads.
    q_at_iq2 = pl.BlockSpec((1, block_q, h),
                            lambda bn_, ik, iq, *_: (bn_, iq, 0))
    kin_at_ik2 = pl.BlockSpec((1, block_k, h),
                              lambda bn_, ik, iq, *_: (kv_of(bn_), ik, 0))
    kout_at_ik2 = pl.BlockSpec((1, block_k, h),
                               lambda bn_, ik, iq, *_: (bn_, ik, 0))
    l_at_iq2 = pl.BlockSpec((1, block_q, 128),
                            lambda bn_, ik, iq, *_: (bn_, iq, 0))

    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel, block_q=block_q, block_k=block_k,
            nq=nq, causal=causal, scale=scale, seq_k=seq_k),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(bn, nk, nq),
            in_specs=[q_at_iq2, kin_at_ik2, kin_at_ik2, q_at_iq2,
                      l_at_iq2, l_at_iq2],
            out_specs=[kout_at_ik2, kout_at_ik2],
            scratch_shapes=[
                pltpu.VMEM((block_k, h), f32),
                pltpu.VMEM((block_k, h), f32),
            ],
        ),
        out_shape=[_sds((bn, sk, h), f32, q, k, v, do, delta, lse),
                   _sds((bn, sk, h), f32, q, k, v, do, delta, lse)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(darr, q, k, v, do, delta, lse)

    if q_heads != kv_heads:
        group = q_heads // kv_heads
        b = bn // q_heads
        dk = dk.reshape(b, kv_heads, group, sk, h).sum(axis=2)
        dk = dk.reshape(b * kv_heads, sk, h)
        dv = dv.reshape(b, kv_heads, group, sk, h).sum(axis=2)
        dv = dv.reshape(b * kv_heads, sk, h)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# chunked variant with carry I/O — the ring-attention inner kernel
# ---------------------------------------------------------------------------

def _flash_chunk_kernel(d_ref, q_ref, k_ref, v_ref, acc_in, m_in, l_in,
                        acc_out, m_out, l_out, acc_s, m_s, l_s, *,
                        block_q: int, block_k: int, nk: int,
                        causal: bool, scale: float):
    """One K/V CHUNK folded into an online-softmax carry.

    Same tile loop as _flash_kernel, but the (acc, m, l) state arrives
    as inputs and leaves UNNORMALIZED as outputs, so a ring step
    (ops/attention.py ring_attention_sharded) can fold one rotating
    chunk per call. `d_ref` (SMEM) holds the TRACED relative offset
    d = q_global_start - k_global_start: causal masking inside the
    kernel is kpos <= qpos + d, which stays correct whichever ring step
    the chunk arrives on. m/l travel in a 128-lane replicated layout
    ([bn, s, 128]) to match the VMEM scratch tiling.
    """
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    d = d_ref[0]

    @pl.when(ik == 0)
    def _load_carry():
        acc_s[:] = acc_in[0]
        m_s[:] = m_in[0]
        l_s[:] = l_in[0]

    if causal:
        live = ik * block_k <= iq * block_q + block_q - 1 + d
    else:
        live = True

    @pl.when(live)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

        if causal:
            kpos = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            qpos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            mask = kpos <= qpos + d
            s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_s[:, :1]
        m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        if causal:
            p = jnp.where(mask, p, 0.0)

        # delayed rescaling, same as _flash_kernel: the corr multiply is
        # an exact no-op (corr == 1) whenever the running max held still
        @pl.when(jnp.logical_not((m_new == m_prev).all()))
        def _rescale():
            corr = jnp.exp(m_prev - m_new)
            acc_s[:] = acc_s[:] * corr
            l_s[:] = l_s[:] * corr

        m_s[:] = jnp.broadcast_to(m_new, m_s.shape)
        l_s[:] = l_s[:] + jnp.broadcast_to(
            p.sum(axis=1, keepdims=True), l_s.shape)
        acc_s[:] = acc_s[:] + jax.lax.dot_general(
            p.astype(v.dtype) if v.dtype == jnp.bfloat16 else p, v,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ik == nk - 1)
    def _store_carry():
        acc_out[0] = acc_s[:]
        m_out[0] = m_s[:]
        l_out[0] = l_s[:]


def flash_attention_chunk(q, k, v, acc, m, l, d,
                          causal: bool = False, block_q: int = 1024,
                          block_k: int = 1024,
                          interpret: Optional[bool] = None,
                          q_heads: int = 1, kv_heads: int = 1):
    """Fold one K/V chunk into an online-softmax carry (pallas).

    Layouts (kernel-native, NO [B,S,N,H] public shape here — the ring
    transposes once outside its scan): q [bn, sq, h]; k/v [bn_kv, sk,
    h]; acc [bn, sq, h] f32; m/l [bn, sq, 128] f32 (lane-replicated).
    `d` is a traced int32 scalar: q_global_start - k_global_start.
    Returns updated (acc, m, l), unnormalized. Finalize with
    acc / max(l, eps) outside (ops/attention._finish agrees).

    GQA: q_heads > kv_heads reads shared K/V tiles via the same
    BlockSpec row remap plain flash uses (_kv_row_map) — grouped
    chunks stay grouped, which is what keeps the ring's ppermute
    volume at the kv-head size.

    sq and sk must be multiples of the (clamped) block sizes — ring
    chunks are equal by construction.
    """
    import math as _math
    bn, sq, h = q.shape
    sk = k.shape[1]
    want = bn // q_heads * kv_heads
    if k.shape[0] != want:
        # loud in the equal-heads case too: grouped K/V passed with the
        # default params would otherwise be silently misread (pallas
        # clamps out-of-range block rows instead of raising)
        raise ValueError(
            f"chunk rows: k has {k.shape[0]}, expected {want} "
            f"(q rows {bn}, q_heads {q_heads}, kv_heads {kv_heads})")
    kv_of = _kv_row_map(q_heads, kv_heads)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if sq % block_q or sk % block_k:
        raise ValueError(
            f"chunk sizes must divide blocks: sq={sq}/{block_q}, "
            f"sk={sk}/{block_k}")
    nq = sq // block_q
    nk = sk // block_k

    kernel = functools.partial(
        _flash_chunk_kernel, block_q=block_q, block_k=block_k, nk=nk,
        causal=causal, scale=1.0 / _math.sqrt(h))

    f32 = jnp.float32
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(bn, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, h), lambda bn_, iq, ik, *_: (bn_, iq, 0)),
            pl.BlockSpec((1, block_k, h),
                         lambda bn_, iq, ik, *_: (kv_of(bn_), ik, 0)),
            pl.BlockSpec((1, block_k, h),
                         lambda bn_, iq, ik, *_: (kv_of(bn_), ik, 0)),
            pl.BlockSpec((1, block_q, h), lambda bn_, iq, ik, *_: (bn_, iq, 0)),
            pl.BlockSpec((1, block_q, 128),
                         lambda bn_, iq, ik, *_: (bn_, iq, 0)),
            pl.BlockSpec((1, block_q, 128),
                         lambda bn_, iq, ik, *_: (bn_, iq, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, h), lambda bn_, iq, ik, *_: (bn_, iq, 0)),
            pl.BlockSpec((1, block_q, 128),
                         lambda bn_, iq, ik, *_: (bn_, iq, 0)),
            pl.BlockSpec((1, block_q, 128),
                         lambda bn_, iq, ik, *_: (bn_, iq, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, h), f32),
            pltpu.VMEM((block_q, 128), f32),
            pltpu.VMEM((block_q, 128), f32),
        ],
    )

    acc2, m2, l2 = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            _sds((bn, sq, h), f32, q, k, v, acc, m, l),
            _sds((bn, sq, 128), f32, q, k, v, acc, m, l),
            _sds((bn, sq, 128), f32, q, k, v, acc, m, l),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(jnp.asarray([d], jnp.int32).reshape(1), q, k, v, acc, m, l)
    return acc2, m2, l2


# ---------------------------------------------------------------------------
# fused paged decode attention — the block-table kernels
# ---------------------------------------------------------------------------
#
# The serving decode hot loop: instead of materializing a
# [B, max_blocks*block_size, n_kv, head_dim] gather per layer per step
# (ops/paged_attention.gather_block_kv — the XLA oracle), the kernels
# walk the int32 block table DIRECTLY. Grid (slot, kv-head, block);
# the K/V BlockSpec index_map resolves logical block i of slot b to its
# physical pool block via the scalar-prefetched table
# (table_ref[b, i]), so each (block_size, head_dim) tile streams
# HBM -> VMEM exactly once and no logical view ever touches HBM.
#
# Quantized (int8/fp8) pools dequantize AT THE VMEM BOUNDARY:
# per-(block, kv-head) absmax scales ride a sibling [num_blocks, n_kv]
# f32 array whose BlockSpec follows the same table indirection, and
# (q * scale).astype(q.dtype) happens on the freshly-landed tile —
# HBM moves 1 byte/elem instead of 2 (bf16) or 4 (f32).
#
# TWO kernels share that walk, trading VMEM for exactness differently:
#
# `fused` (_paged_kernel) — the BITWISE reference. The fused path must
# be able to emit the SAME TOKENS as the gather oracle and the dense
# server with bitwise-equal scores and softmax (tests pin dense ==
# gather-paged == fused-paged greedy/sampled/speculative), so it
# spends VMEM on exactness: per-block score tiles are stashed into an
# (W*g, S) f32 scratch and dequantized V rows into an (S, hd) scratch
# along the sequential block axis, and the LAST block step applies the
# oracle's op order verbatim — mask to -inf, f32 softmax over the full
# row, cast to q.dtype, one (W*g, S) x (S, hd) dot. Scores and
# softmax are bitwise-equal to the oracle's; the final PV contraction
# is the same f32 math but XLA schedules a batched einsum's reduction
# differently from a 2-D dot, so logits agree to ~1 ulp rather than
# bit-for-bit — the same variation the repo already carries between
# its own programs (the oracle's eager and jitted logits differ by the
# same amount, as do its W=1 decode and W-window verify gemms), and
# the reason every serving equivalence contract here is pinned at
# exact TOKENS plus ulp-tight logits. VMEM cost is O(S*(W*g + hd))
# per (slot, head) step, which is what CAPS the usable context: S
# rides the scratch, so smax can't outgrow VMEM.
#
# `fused_online` (_paged_online_kernel) — the O(block) roofline leg.
# The classic flash-attention move applied to the paged walk: the
# kernel carries only the (acc, m, l) online-softmax state —
# (W*g, hd) f32 accumulator plus two lane-replicated (W*g, 128)
# running max/denominator rows — and each K/V block tile is consumed
# the moment it lands (Pallas double-buffers the streamed BlockSpec
# tiles against compute, exactly like the flash kernels above). NO
# scratch has sequence extent, so VMEM no longer bounds smax and the
# HBM traffic is unchanged — pure roofline win at long context. The
# price is the numerics contract: a running-max softmax rescales
# partial accumulators (exp(m_prev - m_new) multiplies) and its
# reduction ORDER differs from the oracle's one-pass jax.nn.softmax,
# so results drift O(eps * nblk) from the oracle — a few ulp at
# serving shapes, NOT bitwise. The equivalence gate for fused_online
# is therefore tolerance-budgeted (logits allclose at a few-ulp rtol;
# greedy tokens identical across the dense/paged/spec sweep), with
# `fused` kept as the bitwise reference. Masking stays EXACT-zero
# (p = where(live, exp(s - m), 0)), so trash/pad blocks contribute
# exactly 0.0 probability mass in both kernels, and both share the
# per-window-row horizon `kpos <= pos0 + wrow` for the decode (W=1)
# and spec-verify window entry points.
#
# Pick `fused` when byte-identity with the dense/gather server is the
# contract (rollback-heavy speculation audits, A/B token equality);
# pick `fused_online` when context length presses VMEM — the knob is
# hpx.serving.paged_kernel = fused | fused_online | gather | auto.

_PAGED_BLOCKS_FILE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "paged_blocks.json")
_paged_blocks_table: Optional[dict] = None


def _load_paged_blocks() -> dict:
    global _paged_blocks_table
    if _paged_blocks_table is None:
        try:
            with open(_PAGED_BLOCKS_FILE) as f:
                _paged_blocks_table = dict(json.load(f))
        except (OSError, ValueError):
            _paged_blocks_table = {}
    return _paged_blocks_table


def resolve_paged_block_src(head_dim: int, kv_dtype: str = "bf16",
                            default: int = 16) -> tuple:
    """The cache block_size `hpx.cache.block_size=auto` resolves to,
    with its source: ``(value, 'env' | 'learned' | 'seed' |
    'default')``.

    Resolution order: HPX_PAGED_BLOCK env > perfdb learned-blocks
    tier (``hpx.perfdb.use_learned_ladders=1`` and the configured
    store holds a usable ``hd<head_dim>x<kv_dtype>`` entry — see
    svc/perfdb) > seed table (benchmarks/flash_tune.py --paged writes
    paged_blocks.json next to this file, same key grammar) >
    `default`.  The source lands in
    ``ContinuousServer.hbm_read_stats()['block_size_source']``."""
    env = os.environ.get("HPX_PAGED_BLOCK")
    if env:
        return int(env), "env"
    # lazy import: svc.perfdb is stdlib-only but lives a layer up;
    # importing at call time keeps ops import-light and cycle-free
    from ..svc import perfdb as _perfdb
    learned = _perfdb.learned_block(head_dim, kv_dtype)
    if learned:
        return int(learned), "learned"
    table = _load_paged_blocks()
    val = table.get(f"hd{head_dim}x{kv_dtype}")
    if val:
        return int(val), "seed"
    return default, "default"


def resolve_paged_block(head_dim: int, kv_dtype: str = "bf16",
                        default: int = 16) -> int:
    """``resolve_paged_block_src`` without the source (the historical
    interface — callers that only need the number)."""
    return resolve_paged_block_src(head_dim, kv_dtype, default)[0]


def _paged_kernel(table_ref, pos_ref, q_ref, k_ref, v_ref, *rest,
                  block_size: int, nblk: int, group: int,
                  quantized: bool):
    """One (slot b, kv-head h, logical block i) grid step.

    q_ref: (1, 1, Wg, hd) the slot's query rows for this kv head
    (window row w, group lane j flattened as r = w*group + j);
    k_ref/v_ref: (1, block_size, 1, hd) the PHYSICAL pool block the
    table maps logical block i to (the index_map did the gather);
    quantized adds ks_ref/vs_ref (1, 1) per-(block, head) scales.
    s_s/v_s scratch accumulate the full logical row along the
    sequential i axis; the last step runs the oracle-order softmax."""
    if quantized:
        ks_ref, vs_ref, o_ref, s_s, v_s = rest
    else:
        o_ref, s_s, v_s = rest
    b = pl.program_id(0)
    i = pl.program_id(2)

    q = q_ref[0, 0]                                # (Wg, hd)
    k = k_ref[0, :, 0, :]                          # (bs, hd)
    v = v_ref[0, :, 0, :]
    if quantized:
        # dequantize at the VMEM boundary — elementwise-identical to
        # the oracle's (pool.astype(f32) * scale).astype(q.dtype)
        k = (k.astype(jnp.float32) * ks_ref[0, 0]).astype(q.dtype)
        v = (v.astype(jnp.float32) * vs_ref[0, 0]).astype(q.dtype)

    # same dtype semantics as the oracle's einsum (no forced f32
    # accumulation — byte-identity beats MXU rate here; the f32 upcast
    # below is exact for bf16/f32 scores)
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())))
    s = s / math.sqrt(q.shape[-1])
    s_s[:, pl.ds(i * block_size, block_size)] = s.astype(jnp.float32)
    v_s[pl.ds(i * block_size, block_size), :] = v.astype(jnp.float32)

    @pl.when(i == nblk - 1)
    def _finish():
        pos0 = pos_ref[b]
        sf = s_s[...]                              # (Wg, S) f32
        kpos = jax.lax.broadcasted_iota(jnp.int32, sf.shape, 1)
        wrow = jax.lax.broadcasted_iota(jnp.int32, sf.shape, 0) // group
        live = kpos <= pos0 + wrow                 # per-window-row horizon
        sf = jnp.where(live, sf, -jnp.inf)
        p = jax.nn.softmax(sf, axis=-1)            # oracle op order
        att = jax.lax.dot_general(
            p.astype(o_ref.dtype), v_s[...].astype(o_ref.dtype),
            (((1,), (0,)), ((), ())))
        o_ref[0, 0] = att.astype(o_ref.dtype)


def paged_online_scratch_shapes(wg_pad: int, head_dim: int) -> list:
    """The fused_online VMEM carry: (acc, m, l) — (W*g, hd) f32
    accumulator plus two lane-replicated (W*g, 128) running-max /
    denominator rows. O(block) BY CONSTRUCTION: the function does not
    even take a sequence length, so no scratch can carry S extent —
    the acceptance gate for the online kernel asserts exactly this."""
    return [
        pltpu.VMEM((wg_pad, head_dim), jnp.float32),   # acc
        pltpu.VMEM((wg_pad, 128), jnp.float32),        # m (running max)
        pltpu.VMEM((wg_pad, 128), jnp.float32),        # l (denominator)
    ]


def _paged_online_kernel(table_ref, pos_ref, q_ref, k_ref, v_ref,
                         *rest, block_size: int, nblk: int, group: int,
                         quantized: bool):
    """One (slot b, kv-head h, logical block i) grid step of the
    online-softmax paged walk.

    Same operands and table indirection as `_paged_kernel`, but the
    carry is the flash (acc, m, l) state (`paged_online_scratch_shapes`)
    instead of the full score/V rows: each freshly-landed K/V tile is
    folded into the running softmax immediately (delayed rescaling —
    the corr multiply only fires when the running max moved, exactly
    the `_flash_kernel` idiom) and the last block step normalizes.
    Masked lanes get EXACT-zero probability (p is where()'d, not just
    exp()'d), so trash/pad blocks contribute 0.0 like the bitwise
    kernel's."""
    if quantized:
        ks_ref, vs_ref, o_ref, acc_s, m_s, l_s = rest
    else:
        o_ref, acc_s, m_s, l_s = rest
    b = pl.program_id(0)
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _init():
        acc_s[:] = jnp.zeros_like(acc_s)
        m_s[:] = jnp.full_like(m_s, _NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)

    q = q_ref[0, 0]                                # (Wg, hd)
    k = k_ref[0, :, 0, :]                          # (bs, hd)
    v = v_ref[0, :, 0, :]
    if quantized:
        # dequantize at the VMEM boundary — elementwise-identical to
        # the oracle's (pool * scale).astype(q.dtype)
        k = (k.astype(jnp.float32) * ks_ref[0, 0]).astype(q.dtype)
        v = (v.astype(jnp.float32) * vs_ref[0, 0]).astype(q.dtype)

    # f32 score accumulation (the flash numerics contract) — this
    # kernel's gate is tolerance-budgeted, so MXU-rate operands with
    # f32 accumulation beat the bitwise kernel's oracle-order dots
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    s = s / math.sqrt(q.shape[-1])                 # (Wg, bs) f32

    pos0 = pos_ref[b]
    kpos = i * block_size + jax.lax.broadcasted_iota(
        jnp.int32, s.shape, 1)
    wrow = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) // group
    live = kpos <= pos0 + wrow                     # per-window-row horizon
    s = jnp.where(live, s, _NEG_INF)

    m_prev = m_s[:, :1]                            # (Wg, 1)
    m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
    p = jnp.exp(s - m_new)
    p = jnp.where(live, p, 0.0)                    # masked lanes: exact 0

    # delayed rescaling: skip the corr multiply on every block where
    # the running max didn't move (corr == exp(0) == 1)
    @pl.when(jnp.logical_not((m_new == m_prev).all()))
    def _rescale():
        corr = jnp.exp(m_prev - m_new)
        acc_s[:] = acc_s[:] * corr
        l_s[:] = l_s[:] * corr

    m_s[:] = jnp.broadcast_to(m_new, m_s.shape)
    l_s[:] = l_s[:] + jnp.broadcast_to(
        p.sum(axis=1, keepdims=True), l_s.shape)
    acc_s[:] = acc_s[:] + jax.lax.dot_general(
        p.astype(v.dtype) if v.dtype == jnp.bfloat16 else p, v,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(i == nblk - 1)
    def _finish():
        # every real row has at least position 0 live, so l > 0; the
        # guard covers only the 8-sublane pad rows (sliced off outside)
        l = l_s[:, :1]
        den = jnp.where(l > 0, l, 1.0)
        o_ref[0, 0] = (acc_s[:] / den).astype(o_ref.dtype)


def fused_paged_attention(q: jax.Array, k_pool: jax.Array,
                          v_pool: jax.Array, table: jax.Array,
                          pos0: jax.Array,
                          k_scale: Optional[jax.Array] = None,
                          v_scale: Optional[jax.Array] = None,
                          interpret: Optional[bool] = None) -> jax.Array:
    """Decode/verify attention that walks the block table in-kernel.

    q: [B, W, n_q, head_dim] post-rope queries (W = 1 for plain decode,
    W = window width for speculative verify); k_pool/v_pool:
    [num_blocks, block_size, n_kv, head_dim] with this step's rows
    ALREADY scattered (write precedes attention, exactly like the
    gather oracle); table: [B, max_blocks] int32; pos0: [B] int32 —
    window row w attends logical positions <= pos0 + w (W = 1: the
    inclusive `<= pos` decode mask). k_scale/v_scale: [num_blocks,
    n_kv] f32 per-(block, head) absmax scales for quantized (int8/fp8)
    pools (None for bf16/f32 pools). Returns att [B, W, n_q, head_dim]
    in q.dtype.

    Every logical block (trash-padded tail included) is processed and
    masked, never skipped — rows past pos0+w contribute exact-zero
    probability, matching `paged_decode_attention` element-for-element:
    bitwise-equal scores and softmax, logits within ~1 ulp (see the
    section comment), same tokens. GQA via the same grouped-query
    reshape, so n_q % n_kv == 0.

    Falls back to interpret mode off-TPU (CPU tier-1 stays green).
    Real-TPU int8 pools want block_size >= 32 (the int8 sublane tile);
    interpret mode takes any block size.

    Runs unchanged inside shard_map on the serving (dp, tp) mesh
    (via utils/jaxcompat): n_q/n_kv here are then the PER-SHARD head
    counts (tp slices the kv-head axis, so the GQA group n_q // n_kv
    is unchanged), the block axis is dp-replicated so the
    scalar-prefetched table's global block ids index the local pool
    directly, and int8/fp8 scales arrive pre-sliced per (block, local
    head) — no kernel-visible difference from the single-device
    call."""
    return _fused_paged_call(q, k_pool, v_pool, table, pos0,
                             k_scale, v_scale, interpret, online=False)


def fused_paged_online_attention(q: jax.Array, k_pool: jax.Array,
                                 v_pool: jax.Array, table: jax.Array,
                                 pos0: jax.Array,
                                 k_scale: Optional[jax.Array] = None,
                                 v_scale: Optional[jax.Array] = None,
                                 interpret: Optional[bool] = None
                                 ) -> jax.Array:
    """`fused_paged_attention` with an in-kernel online softmax —
    the O(block)-scratch variant (`hpx.serving.paged_kernel=
    fused_online`).

    Same operands, same scalar-prefetched table walk, same exact-zero
    masking and decode/spec-verify window semantics as the bitwise
    kernel — only the carry differs: instead of stashing (W*g, S)
    scores + (S, hd) V rows, the kernel streams each K/V block through
    the flash (acc, m, l) state (`paged_online_scratch_shapes` — no
    scratch carries sequence extent), so VMEM stops bounding smax.
    Pallas double-buffers the streamed tiles against compute along the
    sequential block axis.

    Numerics contract (tolerance-budgeted — NOT bitwise): the
    running-max rescales reorder the softmax reduction, so logits
    agree with the gather oracle to a few ulp (O(eps * num_blocks))
    rather than bit-for-bit; greedy tokens are identical across the
    dense/paged/spec test sweep. When byte-identity is the requirement,
    use `fused` — that kernel stays the bitwise reference."""
    return _fused_paged_call(q, k_pool, v_pool, table, pos0,
                             k_scale, v_scale, interpret, online=True)


def _fused_paged_call(q, k_pool, v_pool, table, pos0, k_scale, v_scale,
                      interpret, online: bool) -> jax.Array:
    """Shared launch path for the two paged kernels: identical grid,
    BlockSpec table indirection, quantized-scale plumbing, and
    pad/slice layout — only the kernel body and its scratch differ."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, w, nq, hd = q.shape
    bs = k_pool.shape[1]
    nkv = k_pool.shape[2]
    maxb = table.shape[1]
    if nq % nkv:
        raise ValueError(f"q heads ({nq}) not a multiple of kv heads "
                         f"({nkv})")
    g = nq // nkv
    wg = w * g
    wg_pad = wg + (-wg % 8)          # 8-sublane f32 tile; pad rows are
    seq = maxb * bs                  # garbage, sliced off below

    # [B, W, nkv, g, hd] -> [B, nkv, W*g, hd]: row r = w*g + j
    qk = jnp.moveaxis(q.reshape(b, w, nkv, g, hd), 2, 1)
    qk = qk.reshape(b, nkv, wg, hd)
    if wg_pad != wg:
        qk = jnp.pad(qk, ((0, 0), (0, 0), (0, wg_pad - wg), (0, 0)))

    quantized = k_scale is not None
    kernel = functools.partial(
        _paged_online_kernel if online else _paged_kernel,
        block_size=bs, nblk=maxb, group=g, quantized=quantized)
    if online:
        # the flash carry — O(block), no sequence extent anywhere
        scratch = paged_online_scratch_shapes(wg_pad, hd)
    else:
        # the bitwise kernel banks full rows: O(S * (W*g + hd))
        scratch = [pltpu.VMEM((wg_pad, seq), jnp.float32),
                   pltpu.VMEM((seq, hd), jnp.float32)]

    q_spec = pl.BlockSpec((1, 1, wg_pad, hd),
                          lambda bb, hh, ii, *_: (bb, hh, 0, 0))
    # THE fusion: logical block ii of slot bb reads physical pool
    # block table[bb, ii] straight from the scalar-prefetched table
    kv_spec = pl.BlockSpec(
        (1, bs, 1, hd),
        lambda bb, hh, ii, tref, pref: (tref[bb, ii], 0, hh, 0))
    in_specs = [q_spec, kv_spec, kv_spec]
    operands = [qk, k_pool, v_pool]
    if quantized:
        sc_spec = pl.BlockSpec(
            (1, 1), lambda bb, hh, ii, tref, pref: (tref[bb, ii], hh))
        in_specs += [sc_spec, sc_spec]
        operands += [k_scale, v_scale]

    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, nkv, maxb),
            in_specs=in_specs,
            out_specs=[q_spec],
            scratch_shapes=scratch,
        ),
        out_shape=[_sds((b, nkv, wg_pad, hd), q.dtype, q, k_pool,
                        v_pool)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(table.astype(jnp.int32), pos0.astype(jnp.int32), *operands)[0]

    out = out[:, :, :wg]
    return jnp.moveaxis(out.reshape(b, nkv, w, g, hd), 1, 2
                        ).reshape(b, w, nq, hd)
