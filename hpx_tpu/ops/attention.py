"""Attention ops: flash-style blockwise attention, ring attention
(sequence parallel over the ICI ring), and Ulysses (all_to_all head
parallel).

The reference (HPX) contains no attention — SURVEY.md §5.7 documents
that the nearest structural analogs it DOES have are the halo-exchange
ring (`lax.ppermute`, parallel/halo.py) and the `all_to_all` collective.
These ops are the long-context capability built ON that substrate, as
the driver mandates: ring attention is the stencil halo pattern with an
online-softmax accumulator; Ulysses is the segmented-algorithm pattern
with an all_to_all re-shard.

Shapes follow jax convention: [batch, seq, heads, head_dim] ("BSNH").
All math accumulates in float32 regardless of input dtype (bfloat16
inputs stay bf16 on the wire/MXU, f32 in the softmax accumulator).
"""

from __future__ import annotations

import functools
import math
from typing import Any, Optional

import jax
import jax.numpy as jnp
from ..utils.jaxcompat import shard_map
from jax.sharding import PartitionSpec as P

__all__ = [
    "auto_attention", "reference_attention", "blockwise_attention",
    "ring_attention", "ring_attention_sharded", "ulysses_attention",
    "stripe_sequence", "unstripe_sequence", "ring_positions",
]


def auto_attention(q: jax.Array, k: jax.Array, v: jax.Array,
              causal: bool = False) -> jax.Array:
    """Best-available single-device attention: the pallas flash kernel
    on TPU (bf16 MXU tiles with fp32 accumulation, VMEM-resident online
    softmax; driver-measured 35% MFU at B2/S4096/N8/H128 causal —
    BENCH_r03.json — higher at longer S), XLA blockwise elsewhere.
    Differentiable on both paths (flash carries a custom_vjp)."""
    if jax.default_backend() == "tpu":
        from .attention_pallas import flash_attention
        return flash_attention(q, k, v, causal)
    return blockwise_attention(q, k, v, causal)


def _scale(q: jax.Array) -> jax.Array:
    return q * (1.0 / math.sqrt(q.shape[-1]))


def _pvary(x: jax.Array, axis) -> jax.Array:
    """Mark a constant as device-varying over shard_map axis/axes (newer
    jax tracks varying manual axes; older versions don't need it)."""
    axes = (axis,) if isinstance(axis, str) else tuple(axis)
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axes, to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(x, axes)
    return x


# ---------------------------------------------------------------------------
# reference (materializes the full score matrix — test oracle only)
# ---------------------------------------------------------------------------

def _expand_kv(q: jax.Array, k: jax.Array, v: jax.Array):
    """GQA/MQA on the XLA paths: repeat K/V heads up to the q head
    count (the pallas kernels share tiles via BlockSpec index remaps
    instead — attention_pallas._kv_row_map — and never materialize the
    repeat; these XLA formulations are oracles/fallbacks, so the
    repeat's bandwidth cost is acceptable)."""
    nq, nkv = q.shape[2], k.shape[2]
    if nkv == nq:
        return k, v
    if nq % nkv:
        raise ValueError(f"q heads ({nq}) not a multiple of kv heads "
                         f"({nkv})")
    r = nq // nkv
    return (jnp.repeat(k, r, axis=2), jnp.repeat(v, r, axis=2))


def reference_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        causal: bool = False) -> jax.Array:
    """O(S^2) memory oracle. [B,S,N,H] -> [B,S,N,H]; fewer K/V heads
    (GQA/MQA) broadcast per group."""
    k, v = _expand_kv(q, k, v)
    qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))
    s = jnp.einsum("bqnh,bknh->bnqk", _scale(qf), kf)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bnqk,bknh->bqnh", p, vf)
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# blockwise (flash) attention — single device
# ---------------------------------------------------------------------------

def _online_block(q: jax.Array, k: jax.Array, v: jax.Array,
                  acc: jax.Array, m: jax.Array, l: jax.Array,
                  bias: Optional[jax.Array] = None):
    """One K/V block of online softmax.

    q:[B,Sq,N,H] k,v:[B,Sk,N,H]; acc:[B,Sq,N,H] f32; m,l:[B,Sq,N] f32.
    bias (optional): [Sq,Sk] additive mask (-inf for masked).
    Returns updated (acc, m, l).
    """
    s = jnp.einsum("bqnh,bknh->bqnk", _scale(q.astype(jnp.float32)),
                   k.astype(jnp.float32))
    if bias is not None:
        s = s + bias[None, :, None, :]
    m_new = jnp.maximum(m, s.max(axis=-1))
    # renormalize the old accumulator; -inf rows (nothing seen yet and
    # fully masked block) must contribute exp(0)=... guard NaNs:
    corr = jnp.exp(m - m_new)
    corr = jnp.where(jnp.isfinite(corr), corr, 0.0)
    p = jnp.exp(s - m_new[..., None])
    p = jnp.where(jnp.isfinite(p), p, 0.0)
    l_new = l * corr + p.sum(axis=-1)
    acc_new = acc * corr[..., None] + jnp.einsum(
        "bqnk,bknh->bqnh", p, v.astype(jnp.float32))
    return acc_new, m_new, l_new


def _finish(acc: jax.Array, l: jax.Array, dtype) -> jax.Array:
    den = jnp.where(l > 0, l, 1.0)[..., None]
    return (acc / den).astype(dtype)


def blockwise_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        causal: bool = False,
                        block_k: int = 512) -> jax.Array:
    """Flash-style attention: K/V consumed in blocks with an online
    softmax — O(S) memory. The inner loop is a lax.scan, so XLA sees a
    static program whatever the sequence length. Fewer K/V heads
    (GQA/MQA) broadcast per group."""
    k, v = _expand_kv(q, k, v)
    b, sq, n, h = q.shape
    sk = k.shape[1]
    nblk = -(-sk // block_k)
    pad = nblk * block_k - sk
    if pad:
        kp = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        vp = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    else:
        kp, vp = k, v
    kb = kp.reshape(b, nblk, block_k, n, h).transpose(1, 0, 2, 3, 4)
    vb = vp.reshape(b, nblk, block_k, n, h).transpose(1, 0, 2, 3, 4)

    q_pos = jnp.arange(sq)
    # accumulators derive from q (not fresh constants) so that when this
    # runs INSIDE a shard_map (ulysses_attention) the scan carry has the
    # same varying-manual-axes type as its updated value; XLA folds the
    # multiply-by-zero
    zero_q = q.astype(jnp.float32) * 0.0
    acc0 = zero_q
    m0 = zero_q[..., 0] - jnp.inf
    l0 = zero_q[..., 0]

    def step(carry, inputs):
        acc, m, l = carry
        kblk, vblk, blk_idx = inputs
        k_pos = blk_idx * block_k + jnp.arange(block_k)
        bias = jnp.where(k_pos[None, :] < sk, 0.0, -jnp.inf)
        if causal:
            bias = bias + jnp.where(
                k_pos[None, :] <= q_pos[:, None] + (sk - sq), 0.0,
                -jnp.inf)
        else:
            bias = jnp.broadcast_to(bias, (sq, block_k))
        return _online_block(q, kblk, vblk, acc, m, l, bias), None

    (acc, _m, l), _ = jax.lax.scan(
        step, (acc0, m0, l0), (kb, vb, jnp.arange(nblk)))
    return _finish(acc, l, q.dtype)


# ---------------------------------------------------------------------------
# ring attention — sequence parallel over a mesh axis
# ---------------------------------------------------------------------------


def stripe_sequence(x: jax.Array, p: int, axis: int = 1) -> jax.Array:
    """Contiguous -> STRIPED token layout for a p-way causal ring:
    global token r + p*i moves to slot r*(S/p) + i, so the shard at
    ring position r holds every p-th token (Striped Attention). One
    reshape-transpose; applied to an array sharded over `axis` under
    jit, XLA lowers it to an all_to_all. Why: with contiguous chunks a
    causal ring idles rank r for (p-1-r) of its p steps (future
    chunks are fully masked) — wall clock ~p full chunk-folds. Striped,
    every chunk-pair is HALF-masked with plain local causal offset 0
    or -1, so all ranks work every step: ~p/2 fold-equivalents, ~2x
    on long causal sequences, same collectives."""
    n = x.shape[axis]
    if n % p:
        raise ValueError(f"stripe_sequence: length {n} not divisible "
                         f"by {p}")
    sq = n // p
    xm = jnp.moveaxis(x, axis, 0)
    y = xm.reshape(sq, p, *xm.shape[1:]).swapaxes(0, 1)
    return jnp.moveaxis(y.reshape(n, *xm.shape[1:]), 0, axis)


def unstripe_sequence(x: jax.Array, p: int, axis: int = 1) -> jax.Array:
    """Inverse of stripe_sequence (the same transpose with the factors
    swapped)."""
    n = x.shape[axis]
    if n % p:
        raise ValueError(f"unstripe_sequence: length {n} not divisible "
                         f"by {p}")
    return stripe_sequence(x, n // p, axis=axis)


def ring_positions(rank, nshards: int, sq: int, striped: bool):
    """GLOBAL token positions of ring shard `rank`: contiguous shards
    own [rank*sq, (rank+1)*sq); striped shards own rank, rank+p, ...
    THE one definition — the ring paths and RoPE all use it, so the
    layouts can never diverge."""
    if striped:
        return rank + nshards * jnp.arange(sq)
    return rank * sq + jnp.arange(sq)


def ring_offset(idx, src, sq: int, striped: bool):
    """The kernels' causal offset d for chunk (q-rank idx, k-rank src):
    contiguous d = q_global_start - k_global_start; striped layouts
    reduce to d = 0 (src <= idx) or -1 — see stripe_sequence."""
    if striped:
        return jnp.where(src <= idx, 0, -1).astype(jnp.int32)
    return (idx - src) * sq


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, mesh: Any,
                   axis: str = "sp", causal: bool = False,
                   striped: bool = False) -> jax.Array:
    """Sequence-parallel attention: q/k/v sharded on `axis` along seq.

    Each device keeps its Q chunk resident and walks the WHOLE sequence
    by rotating K/V chunks around the ICI ring (`lax.ppermute` — the
    1d_stencil halo pattern, SURVEY.md §5.7), folding each arriving
    chunk into an online-softmax accumulator. Peak memory per chip is
    O(S/P); bandwidth is the ring's, which is exactly what the halos
    already ride.

    Causal masking is positional: chunk ownership gives each device its
    global offset, so masking stays correct whatever step the chunk
    arrives on (full-chunk skips still compute — uniform work per step
    keeps the ring in lockstep, the standard TPU tradeoff).

    striped=True (causal long-context): stripe the sequence over the
    ring first (one all_to_all each way), so every rank does balanced
    half-work each step instead of idling on future chunks — ~2x
    causal wall clock; see stripe_sequence.
    """
    nshards = mesh.shape[axis]
    spec = P(None, axis, None, None)

    def run(q, k, v):
        if striped:
            q, k, v = (stripe_sequence(x, nshards) for x in (q, k, v))

        def body(qc, kc, vc):
            return ring_attention_sharded(qc, kc, vc, axis, nshards,
                                          causal, use_flash=None,
                                          striped=striped)

        out = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                        out_specs=spec)(q, k, v)
        if striped:
            out = unstripe_sequence(out, nshards)
        return out

    return jax.jit(run)(q, k, v)


def ring_attention_sharded(qc: jax.Array, kc: jax.Array, vc: jax.Array,
                           axis: str, nshards: int,
                           causal: bool = False,
                           use_flash: Optional[bool] = None,
                           striped: bool = False) -> jax.Array:
    """The per-shard ring body, callable from INSIDE an enclosing
    shard_map (e.g. a sharded transformer step). The ring loop is a
    lax.scan, so reverse-mode AD works (scan transposes; the ppermute
    transpose is the inverse rotation) — training steps can
    differentiate straight through the ring.

    use_flash (default None = flash on TPU): fold each arriving chunk
    with the pallas chunk kernel (attention_pallas.flash_attention_chunk)
    instead of the XLA online block — 2-8x faster on TPU, and
    DIFFERENTIABLE: _ring_flash carries a custom_vjp whose backward
    replays the ring with the pallas flash-backward kernels
    (attention_pallas.flash_attention_bwd), rotating dK/dV partial
    accumulators around the ICI ring alongside the chunks.

    striped=True: chunks are in the stripe_sequence layout (shard r
    holds tokens r, r+p, ...). Causal masking then reduces to a plain
    local causal mask with offset 0 (k-rank <= q-rank) or -1 — EVERY
    ring step does balanced half-work instead of rank r idling for its
    future chunks, ~2x wall-clock on causal rings. Layout conversion
    (an all_to_all) is the caller's job: stripe once outside, run many
    layers striped, unstripe once.
    """
    if use_flash is None:
        use_flash = jax.default_backend() == "tpu"
    if use_flash:
        if nshards == 1:
            # degenerate ring: plain flash (custom_vjp) — skips the
            # scan/ppermute wrapping and the unnormalized f32 carry;
            # handles GQA natively (grouped K/V tiles)
            from .attention_pallas import flash_attention
            return flash_attention(qc, kc, vc, causal)
        # GQA rides the ring GROUPED: the chunk kernel reads shared
        # K/V tiles via the same BlockSpec row remap plain flash uses,
        # and the backward's dK/dV partials accumulate (group-summed)
        # in the kv-head layout — every ppermute hop moves only the
        # kv heads, the whole wire saving of GQA.
        return _ring_flash(qc, kc, vc, axis, nshards, causal, striped)
    b, sq, n, h = qc.shape
    idx = jax.lax.axis_index(axis)
    q_pos = ring_positions(idx, nshards, sq, striped)

    # accumulators derive from qc (already device-varying), so the scan
    # carry's varying manual axes match the updated values whatever
    # enclosing mesh axes exist
    zero_q = qc.astype(jnp.float32) * 0.0
    acc = zero_q
    m = zero_q[..., 0] - jnp.inf
    l = zero_q[..., 0]

    perm = [(i, (i + 1) % nshards) for i in range(nshards)]

    def step(carry, t):
        acc, m, l, kc, vc = carry
        # chunk arriving at step t started at ring position idx-t
        src = (idx - t) % nshards
        k_pos = ring_positions(src, nshards, sq, striped)
        if causal:
            bias = jnp.where(k_pos[None, :] <= q_pos[:, None],
                             0.0, -jnp.inf)
        else:
            bias = jnp.zeros((sq, sq), jnp.float32)
        # GQA: the ring circulates the GROUPED [B,S/P,Nkv,H] chunks —
        # every ppermute hop moves only the kv heads — and broadcasts
        # per group locally just for this step's fold (AD transposes
        # the repeat to a group-sum, so dK/dV stay grouped on the wire)
        ke, ve = _expand_kv(qc, kc, vc)
        acc, m, l = _online_block(qc, ke, ve, acc, m, l, bias)
        # rotate AFTER folding; ppermute rides the ICI ring
        kc = jax.lax.ppermute(kc, axis, perm)
        vc = jax.lax.ppermute(vc, axis, perm)
        return (acc, m, l, kc, vc), None

    (acc, m, l, _kc, _vc), _ = jax.lax.scan(
        step, (acc, m, l, kc, vc), jnp.arange(nshards))
    return _finish(acc, l, qc.dtype)


def _ring_blk(sq: int, cap: int) -> int:
    """Largest kernel block that divides the chunk length (the chunk
    and backward kernels have no padding path), sublane-aligned when
    possible."""
    blk = math.gcd(sq, cap)
    if blk % 8:
        blk = sq
    return blk


def _ring_flash_fwd_impl(qc, kc, vc, axis, nshards, causal,
                         striped=False):
    """Ring attention with the pallas chunk kernel as the inner fold.

    Layout transposes to kernel-native [B*N, S/P, H] happen ONCE
    outside the ring scan; each step folds the arriving K/V chunk via
    flash_attention_chunk with the traced global offset
    d = (idx - src) * sq, then rotates K/V with ppermute. Returns the
    public-layout output plus the residuals the backward needs
    (kernel-layout operands, normalized output, row logsumexp).
    """
    from .attention_pallas import _kernel_layout, flash_attention_chunk

    b, sq, n, h = qc.shape
    nkv = kc.shape[2]
    blk = _ring_blk(sq, 1024)
    idx = jax.lax.axis_index(axis)

    qt = _kernel_layout(qc)
    kt = _kernel_layout(kc)
    vt = _kernel_layout(vc)

    # accumulators derive from qt so the scan carry's varying manual
    # axes match inside whatever enclosing mesh axes exist
    zq = qt.astype(jnp.float32) * 0.0
    acc = zq
    m = zq[:, :, :1] - jnp.full((128,), 1e30, jnp.float32)
    l = zq[:, :, :1] + jnp.zeros((128,), jnp.float32)

    perm = [(i, (i + 1) % nshards) for i in range(nshards)]

    def step(carry, t):
        acc, m, l, kc_, vc_ = carry
        src = (idx - t) % nshards
        d = ring_offset(idx, src, sq, striped)
        acc, m, l = flash_attention_chunk(qt, kc_, vc_, acc, m, l, d,
                                          causal=causal, block_q=blk,
                                          block_k=blk, q_heads=n,
                                          kv_heads=nkv)
        kc_ = jax.lax.ppermute(kc_, axis, perm)
        vc_ = jax.lax.ppermute(vc_, axis, perm)
        return (acc, m, l, kc_, vc_), None

    # after nshards rotations the K/V chunks return home, so kt/vt are
    # valid residuals for the backward replay
    (acc, m, l, _kc, _vc), _ = jax.lax.scan(
        step, (acc, m, l, kt, vt), jnp.arange(nshards))

    l1 = l[:, :, :1]
    m1 = m[:, :, :1]
    den = jnp.where(l1 > 0, l1, 1.0)
    ot = (acc / den).astype(qc.dtype)              # [bn, sq, h]
    # one lane of the row logsumexp (the backward re-broadcasts)
    lse = jnp.where(l1 > 0, m1 + jnp.log(den), 0.0)
    out = jnp.moveaxis(ot.reshape(b, n, sq, h), 1, 2)
    return out, (qt, kt, vt, ot, lse)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ring_flash(qc: jax.Array, kc: jax.Array, vc: jax.Array,
                axis: str, nshards: int, causal: bool,
                striped: bool = False) -> jax.Array:
    return _ring_flash_fwd_impl(qc, kc, vc, axis, nshards, causal,
                                striped)[0]


def _ring_flash_bwd(axis, nshards, causal, striped, res, g):
    """Ring-attention backward: replay the forward's chunk rotation;
    each step runs the pallas flash-backward kernels on the arriving
    chunk (attention_pallas.flash_attention_bwd with the traced offset
    d), accumulating dQ locally while dK/dV partial sums travel AROUND
    THE RING with their chunks — after nshards rotations each chunk's
    gradient arrives back at its owner, the same lockstep schedule the
    forward uses."""
    from .attention_pallas import (_kernel_layout, bwd_prep,
                                   flash_attention_bwd)

    qt, kt, vt, ot, lse = res
    b, sq, n, h = g.shape                      # public [B, S/P, N, H]
    nkv = kt.shape[0] // b                     # kv heads (grouped wire)
    blk = _ring_blk(sq, 512)
    idx = jax.lax.axis_index(axis)
    dot_ = _kernel_layout(g).astype(qt.dtype)
    delta128, lse128 = bwd_prep(dot_, ot, lse)

    perm = [(i, (i + 1) % nshards) for i in range(nshards)]
    zf = qt.astype(jnp.float32) * 0.0
    zkv = kt.astype(jnp.float32) * 0.0

    def step(carry, t):
        dq, dk, dv, kr, vr = carry
        src = (idx - t) % nshards
        d = ring_offset(idx, src, sq, striped)
        dq_p, dk_p, dv_p = flash_attention_bwd(
            qt, kr, vr, dot_, delta128, lse128, d, causal=causal,
            block_q=blk, block_k=blk, q_heads=n, kv_heads=nkv)
        dq = dq + dq_p
        dk = dk + dk_p
        dv = dv + dv_p
        kr = jax.lax.ppermute(kr, axis, perm)
        vr = jax.lax.ppermute(vr, axis, perm)
        dk = jax.lax.ppermute(dk, axis, perm)
        dv = jax.lax.ppermute(dv, axis, perm)
        return (dq, dk, dv, kr, vr), None

    (dq, dk, dv, _kr, _vr), _ = jax.lax.scan(
        step, (zf, zkv, zkv, kt, vt), jnp.arange(nshards))

    def back(x, heads, dtype):
        return jnp.moveaxis(x.reshape(b, heads, sq, h), 1,
                            2).astype(dtype)

    return (back(dq, n, qt.dtype), back(dk, nkv, kt.dtype),
            back(dv, nkv, vt.dtype))


_ring_flash.defvjp(_ring_flash_fwd_impl, _ring_flash_bwd)


# ---------------------------------------------------------------------------
# Ulysses — all_to_all head parallelism
# ---------------------------------------------------------------------------

def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array, mesh: Any,
                      axis: str = "sp", causal: bool = False,
                      use_flash: Optional[bool] = None) -> jax.Array:
    """DeepSpeed-Ulysses style sequence parallelism: inputs sharded on
    seq; one all_to_all re-shards to (full seq × heads/P), attention
    runs locally per head group, a second all_to_all restores the seq
    sharding. Requires num_heads % axis_size == 0.

    This is the `all_to_all` collective of the reference's collectives
    module (SURVEY.md §5.7) applied to the attention layout swap; on
    TPU both all_to_alls are single fused ICI ops.

    use_flash (default None = flash on TPU): the local attention uses
    the pallas flash kernel. Differentiable either way — flash carries
    a custom_vjp through the pallas backward kernels; blockwise
    differentiates through the XLA scan.
    """
    nshards = mesh.shape[axis]
    n = q.shape[2]
    if n % nshards:
        raise ValueError(f"heads ({n}) not divisible by mesh axis "
                         f"({nshards}) — use ring_attention")
    if k.shape[2] % nshards:
        # GQA with fewer kv heads than ring shards: broadcast up front
        # (the head all_to_all needs every axis to split evenly)
        k, v = _expand_kv(q, k, v)
    flash = (jax.default_backend() == "tpu" if use_flash is None
             else use_flash)
    spec = P(None, axis, None, None)

    def body(qc, kc, vc):
        def seq_to_heads(x):
            # [B, S/P, N, H] -> [B, S, N/P, H] (tiled all_to_all splits
            # the head axis across the ring and concatenates sequence)
            return jax.lax.all_to_all(x, axis, split_axis=2,
                                      concat_axis=1, tiled=True)

        def heads_to_seq(x):
            # [B, S, N/P, H] -> [B, S/P, N, H]
            return jax.lax.all_to_all(x, axis, split_axis=1,
                                      concat_axis=2, tiled=True)

        qh, kh, vh = seq_to_heads(qc), seq_to_heads(kc), seq_to_heads(vc)
        # local attention sees the FULL sequence for its head group, so
        # the flash kernel drops straight in on TPU
        if flash:
            from .attention_pallas import flash_attention
            out = flash_attention(qh, kh, vh, causal=causal)
        else:
            out = blockwise_attention(qh, kh, vh, causal=causal)
        return heads_to_seq(out)

    return jax.jit(shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=spec))(q, k, v)
