"""Stencil kernels: 3-point heat update, single- and multi-step.

Reference analog: the `heat_part` inner loop of examples/1d_stencil/
1d_stencil_4.cpp (u'[i] = u[i] + k*dt/dx^2 * (u[i-1] - 2u[i] + u[i+1]),
periodic neighbors) — the Mcells/s hot loop of BASELINE config #2.

TPU-first design: a single heat step is HBM-bandwidth-bound (read u, write
u'). The win is fusing T steps per dispatch:
  * pallas_multistep: whole array resident in VMEM, T updates without
    touching HBM in between — compute-bound instead of HBM-bound for
    arrays that fit VMEM (~<=2M f32).
  * xla_multistep: lax.fori_loop of the fused roll-expression under jit —
    works at any size, one HBM round-trip per step.
Both are shape-static, branch-free, and VPU-friendly (8x128 lanes; arrays
are laid out 2D (rows, 128)).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

LANES = 128


def heat_step(u: jax.Array, coef: float) -> jax.Array:
    """One periodic 3-point heat update on a 1-D array (XLA-fused)."""
    left = jnp.roll(u, 1)
    right = jnp.roll(u, -1)
    return u + coef * (left - 2.0 * u + right)


@functools.partial(jax.jit, static_argnames=("steps",))
def xla_multistep(u: jax.Array, coef: jax.Array, steps: int) -> jax.Array:
    """T fused steps via fori_loop in ONE compiled program."""
    def body(_i, s):
        return heat_step(s, coef)
    return jax.lax.fori_loop(0, steps, body, u)


def _pallas_kernel(u_ref, coef_ref, out_ref, *, steps: int):
    """Whole-array-in-VMEM multi-step kernel.

    Layout: (rows, 128). Periodic 1-D neighbor access on the flattened
    view maps to lane/row shifts: left neighbor = roll(+1), which in 2-D
    is a lane roll with row-carry; implemented with jnp.roll on the 2-D
    block (cheap VPU shuffles) after adjusting the carry column.
    """
    u0 = u_ref[:]
    coef = coef_ref[0]
    col = jax.lax.broadcasted_iota(jnp.int32, u0.shape, 1)
    first_col = col == 0
    last_col = col == LANES - 1

    from jax.experimental.pallas import tpu as pltpu

    def one(_i, u):
        # flattened roll(+1): shift lanes right by one; column 0 takes the
        # previous row's lane 127 (row 0 wraps to the last row). Column
        # patch via iota-mask where (a scatter would not lower on TPU);
        # shifts use pltpu.roll — Mosaic's native circular shift.
        lane_r = pltpu.roll(u, 1, axis=1)
        carry_r = pltpu.roll(u[:, LANES - 1:], 1, axis=0)  # prev row's last
        left = jnp.where(first_col, carry_r, lane_r)
        # flattened roll(-1): shift lanes left; last lane takes next row's
        # lane 0.
        # pltpu.roll requires non-negative shifts: roll by size-1
        lane_l = pltpu.roll(u, LANES - 1, axis=1)
        carry_l = pltpu.roll(u[:, :1], u.shape[0] - 1, axis=0)  # next row's first
        right = jnp.where(last_col, carry_l, lane_l)
        return u + coef * (left - 2.0 * u + right)

    # fori_loop (not Python unroll): bounds VMEM liveness to one
    # iteration's temporaries regardless of `steps`
    out_ref[:] = jax.lax.fori_loop(0, steps, one, u0)


@functools.partial(jax.jit, static_argnames=("steps",))
def pallas_multistep(u: jax.Array, coef, steps: int) -> jax.Array:
    """T steps with the state held in VMEM throughout (zero intermediate
    HBM traffic). Requires len(u) % 128 == 0 and the array to fit VMEM."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = u.shape[0]
    assert n % LANES == 0, "pallas stencil requires length % 128 == 0"
    u2 = u.reshape(n // LANES, LANES)
    coef_arr = jnp.asarray([coef], dtype=u.dtype)

    out = pl.pallas_call(
        functools.partial(_pallas_kernel, steps=steps),
        out_shape=jax.ShapeDtypeStruct(u2.shape, u2.dtype),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
    )(u2, coef_arr)
    return out.reshape(n)


# Working set in the kernel is ~5 arrays (state + roll/where temporaries);
# 512K f32 = 2 MB each keeps us ~10 MB, under the 16 MB scoped-VMEM limit.
_VMEM_F32_LIMIT = 1 << 19


@functools.partial(jax.jit, static_argnames=("steps", "use_pallas"))
def multistep(u: jax.Array, coef: jax.Array, steps: int,
              use_pallas: Optional[bool] = None) -> jax.Array:
    """Best-available T-step stencil: pallas when the array fits VMEM.

    Auto mode only picks pallas on a real TPU backend — the mosaic
    kernel doesn't run on the CPU test platform."""
    if use_pallas is None:
        use_pallas = (jax.default_backend() not in ("cpu",) and
                      u.shape[0] % LANES == 0 and
                      u.shape[0] <= _VMEM_F32_LIMIT)
    if use_pallas:
        return pallas_multistep(u, coef, steps)
    return xla_multistep(u, coef, steps)
