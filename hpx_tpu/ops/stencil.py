"""Stencil kernels: 3-point heat update, single- and multi-step.

Reference analog: the `heat_part` inner loop of examples/1d_stencil/
1d_stencil_4.cpp (u'[i] = u[i] + k*dt/dx^2 * (u[i-1] - 2u[i] + u[i+1]),
periodic neighbors) — the Mcells/s hot loop of BASELINE config #2.

TPU-first design: a single heat step is HBM-bandwidth-bound (read u, write
u'). The win is fusing T steps per dispatch:
  * pallas_multistep: whole array resident in VMEM, T updates without
    touching HBM in between — compute-bound instead of HBM-bound for
    arrays that fit VMEM (~<=2M f32).
  * xla_multistep: lax.fori_loop of the fused roll-expression under jit —
    works at any size, one HBM round-trip per step.
Both are shape-static, branch-free, and VPU-friendly (8x128 lanes; arrays
are laid out 2D (rows, 128)).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

LANES = 128


def heat_step(u: jax.Array, coef: float) -> jax.Array:
    """One periodic 3-point heat update on a 1-D array (XLA-fused)."""
    left = jnp.roll(u, 1)
    right = jnp.roll(u, -1)
    return u + coef * (left - 2.0 * u + right)


@functools.partial(jax.jit, static_argnames=("steps",))
def xla_multistep(u: jax.Array, coef: jax.Array, steps: int) -> jax.Array:
    """T fused steps via fori_loop in ONE compiled program."""
    def body(_i, s):
        return heat_step(s, coef)
    return jax.lax.fori_loop(0, steps, body, u)


def _pallas_kernel(u_ref, coef_ref, out_ref, *, steps: int):
    """Whole-array-in-VMEM multi-step kernel.

    Layout: (rows, 128). Periodic 1-D neighbor access on the flattened
    view maps to lane/row shifts: left neighbor = roll(+1), which in 2-D
    is a lane roll with row-carry; implemented with jnp.roll on the 2-D
    block (cheap VPU shuffles) after adjusting the carry column.
    """
    u0 = u_ref[:]
    coef = coef_ref[0]
    col = jax.lax.broadcasted_iota(jnp.int32, u0.shape, 1)
    first_col = col == 0
    last_col = col == LANES - 1

    from jax.experimental.pallas import tpu as pltpu

    def one(_i, u):
        # flattened roll(+1): shift lanes right by one; column 0 takes the
        # previous row's lane 127 (row 0 wraps to the last row). Column
        # patch via iota-mask where (a scatter would not lower on TPU);
        # shifts use pltpu.roll — Mosaic's native circular shift.
        lane_r = pltpu.roll(u, 1, axis=1)
        carry_r = pltpu.roll(u[:, LANES - 1:], 1, axis=0)  # prev row's last
        left = jnp.where(first_col, carry_r, lane_r)
        # flattened roll(-1): shift lanes left; last lane takes next row's
        # lane 0.
        # pltpu.roll requires non-negative shifts: roll by size-1
        lane_l = pltpu.roll(u, LANES - 1, axis=1)
        carry_l = pltpu.roll(u[:, :1], u.shape[0] - 1, axis=0)  # next row's first
        right = jnp.where(last_col, carry_l, lane_l)
        return u + coef * (left - 2.0 * u + right)

    # fori_loop (not Python unroll): bounds VMEM liveness to one
    # iteration's temporaries regardless of `steps`
    out_ref[:] = jax.lax.fori_loop(0, steps, one, u0)


@functools.partial(jax.jit, static_argnames=("steps",))
def pallas_multistep(u: jax.Array, coef, steps: int) -> jax.Array:
    """T steps with the state held in VMEM throughout (zero intermediate
    HBM traffic). Requires len(u) % 128 == 0 and the array to fit VMEM."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = u.shape[0]
    assert n % LANES == 0, "pallas stencil requires length % 128 == 0"
    u2 = u.reshape(n // LANES, LANES)
    coef_arr = jnp.asarray([coef], dtype=u.dtype)

    out = pl.pallas_call(
        functools.partial(_pallas_kernel, steps=steps),
        out_shape=jax.ShapeDtypeStruct(u2.shape, u2.dtype),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
    )(u2, coef_arr)
    return out.reshape(n)


# Working set in the kernel is ~5 arrays (state + roll/where temporaries);
# 512K f32 = 2 MB each keeps us ~10 MB, under the 16 MB scoped-VMEM limit.
_VMEM_F32_LIMIT = 1 << 19


def _pallas_blocked_kernel(u_ref, edges_ref, coef_ref, out_ref):
    """ONE heat step on a (R, 128) slab streamed from HBM.

    Flattened-order neighbors in the (rows, 128) layout are lane shifts
    with a row carry, computed with the SLAB-periodic wrap (the slab's
    first/last elements borrow from its own far edge). The 2 elements
    per slab that wrap wrongly are patched IN-KERNEL from `edges_ref`
    (SMEM: [grid, 2] true global neighbors, 8 bytes per slab gathered
    once in XLA) — so ONE program streams one input + one output
    (8 B/cell, the HBM roofline's assumption). The round-1..3 variant
    patched them with a host-side scatter instead, which forced a
    second full pass over `out` and capped the bench at ~61% of roof.
    Separate halo-block INPUTS (vs these SMEM scalars) were measured to
    stall the DMA pipeline (~15 points of roof); XLA's roll/concat
    lowering of the same step materializes shifted copies (~4x
    traffic)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    i = pl.program_id(0)
    u = u_ref[:]
    coef = coef_ref[0]
    col = jax.lax.broadcasted_iota(jnp.int32, u.shape, 1)
    row = jax.lax.broadcasted_iota(jnp.int32, u.shape, 0)

    lane_r = pltpu.roll(u, 1, axis=1)
    carry_r = pltpu.roll(u[:, LANES - 1:], 1, axis=0)
    left = jnp.where(col == 0, carry_r, lane_r)
    first_cell = jnp.logical_and(row == 0, col == 0)
    left = jnp.where(first_cell, edges_ref[i, 0], left)

    lane_l = pltpu.roll(u, LANES - 1, axis=1)
    carry_l = pltpu.roll(u[:, :1], u.shape[0] - 1, axis=0)
    right = jnp.where(col == LANES - 1, carry_l, lane_l)
    last_cell = jnp.logical_and(row == u.shape[0] - 1, col == LANES - 1)
    right = jnp.where(last_cell, edges_ref[i, 1], right)

    out_ref[:] = u + coef * ((left + right) - 2.0 * u)


_BLOCK_ROWS = 2048           # 1 MB/slab: deep DMA pipeline; 8192 looked
                             # ~5% faster in the r4 sweep but OOMs the
                             # 16 MB scoped VMEM under some jit wrappings
                             # (5 live slab temporaries x 4 MB)


@functools.partial(jax.jit, static_argnames=("interpret",))
def pallas_heat_step(u: jax.Array, coef,
                     interpret: bool = False) -> jax.Array:
    """Single periodic heat step for arrays too big for VMEM: slabs
    stream through a 1-D grid with the global-periodic seam neighbors
    fed as per-slab SMEM scalars. Requires len(u) % 128 == 0 and
    rows % block == 0 (the benchmark shapes; use heat_step_best for
    automatic fallback)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = u.shape[0]
    rows = n // LANES
    r = min(_BLOCK_ROWS, rows)
    assert n % LANES == 0 and rows % r == 0 and r % 8 == 0, (n, rows, r)
    u2 = u.reshape(rows, LANES)
    grid = rows // r

    # true global neighbors of each slab's first/last element — a tiny
    # fused gather (2 scalars per slab)
    import numpy as _np
    starts = jnp.asarray(_np.arange(grid) * r * LANES, jnp.int32)
    edges = jnp.stack([u[(starts - 1) % n],
                       u[(starts + r * LANES) % n]], axis=1)

    out = pl.pallas_call(
        _pallas_blocked_kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((r, LANES), lambda i: (i, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((r, LANES), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(u2.shape, u2.dtype),
        interpret=interpret,
    )(u2, edges, jnp.asarray([coef], dtype=u.dtype)).reshape(n)
    return out


def heat_step_best(u: jax.Array, coef) -> jax.Array:
    """Best-available single step: the blocked pallas kernel on TPU
    when shapes allow, the XLA roll formulation otherwise."""
    n = u.shape[0]
    rows = n // LANES if n % LANES == 0 else 0
    r = min(_BLOCK_ROWS, rows) if rows else 0
    # == "tpu", not "not cpu": the kernel is Mosaic-only — a GPU backend
    # must take the XLA path, not crash in pallas lowering (advisor r2)
    if (jax.default_backend() == "tpu" and rows
            and rows % r == 0 and r % 8 == 0):
        return pallas_heat_step(u, coef)
    return heat_step(u, coef)


@functools.partial(jax.jit, static_argnames=("steps", "use_pallas"))
def multistep(u: jax.Array, coef: jax.Array, steps: int,
              use_pallas: Optional[bool] = None) -> jax.Array:
    """Best-available T-step stencil: pallas when the array fits VMEM.

    Auto mode only picks pallas on a real TPU backend — the mosaic
    kernel runs neither on the CPU test platform nor on GPU."""
    if use_pallas is None:
        use_pallas = (jax.default_backend() == "tpu" and
                      u.shape[0] % LANES == 0 and
                      u.shape[0] <= _VMEM_F32_LIMIT)
    if use_pallas:
        return pallas_multistep(u, coef, steps)
    return xla_multistep(u, coef, steps)
