"""Gather-based paged decode attention over block tables.

The device side of the `hpx_tpu/cache` subsystem: K/V for every
request lives in one preallocated per-layer pool of fixed-size blocks
(`[num_blocks, block_size, n_kv, head_dim]`), and a per-step int32
block table (`cache/page_table.py`) maps each slot's logical positions
to physical blocks. This module is pure jit-safe array plumbing — no
host state, no syncs — so the serving layer can compose it with its
projections while the numerics stay in one place.

Numerical contract: `paged_decode_attention` is element-for-element the
attention core of `models/serving._block_decode_rows` — same einsum
contractions, same contraction lengths (`max_blocks * block_size` rows
gathered in logical order == the dense `smax` rows), same -inf mask and
f32 softmax. Rows past a slot's position are masked to exact-zero
probability, so the garbage content of pad/trash blocks contributes
exactly 0.0 — paged and dense servers emit byte-identical tokens.

The gather materializes a `[B, S, n_kv, head_dim]` view per layer —
the XLA-oracle formulation, and the DESIGNATED oracle module: hpxlint
HPX010 flags `pool[table]`-shaped gathers anywhere else in the serving
hot paths. The fused Pallas kernels that walk the block table in VMEM
(`ops/attention_pallas.fused_paged_attention` and its O(block)-scratch
online-softmax sibling `fused_paged_online_attention`) are the
production decode paths; `fused=True` / `fused="online"` on the two
attention entry points routes through them, and the gather formulation
here is what both are tested against (exact tokens; ulp-tight logits
for `fused`, tolerance-budgeted for `fused="online"` — see the
kernels' numerics contracts).

Quantized KV (`hpx.cache.kv_dtype=int8` or `fp8`): pools store
quantized blocks with per-(block, kv-head) symmetric-absmax scales in
a sibling `[num_blocks, n_kv]` f32 array (the scheme of
`models/quant.py`, applied per block instead of per output channel —
paged blocks make per-block mixed precision natural). int8 rounds onto
the 127-level integer ladder; fp8 (e4m3) scales the block absmax onto
±448 and lets the float8 cast round — both 1 byte/elem. The `*_q`
scatter variants pick the grid off the pool's dtype, so every code
path below serves both. Writes quantize at the frontier: the `*_q`
variants read-modify-write the touched block (dequantize with the old
scale, insert the new rows, recompute the block's absmax, requantize).
Requantization of UNTOUCHED rows is exact whenever the block absmax
didn't move (int8: max|q| == 127 by construction so the recomputed
scale is bit-identical; fp8: the e4m3 cast of an unchanged quotient
reproduces itself), and bounded by one rounding step when it did. The
gather side dequantizes with the same elementwise ops the kernels use
at their VMEM boundary ((q * scale).astype(compute)), so
gather-quantized and fused-quantized agree exactly like their bf16
twins.

Sharded serving (shard_map on a (dp, tp) mesh): every function here is
written against LOCAL shapes only — `n_kv` and `n_q` are read off the
arrays, GQA group size is `n_q // n_kv`, and block ids index the pool's
block axis directly — so the same code runs per-shard unchanged. The
serving layer shards pools/scales over tp on the kv-head axis and
REPLICATES the block axis over dp (`BlockAllocator.pool_pspec`), which
is exactly what keeps each shard's `pool[table]` gather shard-local:
tables carry global block ids, and every id resolves on every dp
shard. Nothing in this module may introduce a cross-shard collective.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..models.quant import _quantize, _quantize_fp8
from .attention_pallas import (fused_paged_attention,
                               fused_paged_online_attention)

__all__ = [
    "gather_block_kv",
    "paged_decode_attention",
    "paged_window_attention",
    "quantize_blocks",
    "scatter_blocks",
    "scatter_blocks_q",
    "scatter_seq_blocks",
    "scatter_seq_blocks_q",
    "scatter_token",
    "scatter_token_q",
    "scatter_window",
    "scatter_window_q",
]


def gather_block_kv(pool: jax.Array, table: jax.Array,
                    scale: jax.Array = None,
                    out_dtype=None) -> jax.Array:
    """Materialize logical K or V rows from a block pool.

    pool: [num_blocks, block_size, n_kv, head_dim]; table: [B,
    max_blocks] int32. Returns [B, max_blocks * block_size, n_kv,
    head_dim] — slot b's logical row p at index p (pad blocks yield
    garbage rows the causal mask must exclude).

    For quantized (int8/fp8) pools pass `scale` ([num_blocks, n_kv]
    f32) and the compute `out_dtype`: blocks dequantize with the same
    elementwise ops the fused kernels apply at their VMEM boundary
    ((q * scale).astype(out_dtype)), keeping the quantized paths
    exactly comparable."""
    g = pool[table]                       # [B, maxb, bs, nkv, hd]
    b, m, s, n, h = g.shape
    if scale is not None:
        sc = scale[table]                 # [B, maxb, nkv]
        g = (g.astype(jnp.float32) * sc[:, :, None, :, None]).astype(
            out_dtype if out_dtype is not None else jnp.bfloat16)
    return g.reshape(b, m * s, n, h)


def quantize_blocks(rows: jax.Array, dtype=jnp.int8):
    """Symmetric-absmax quantization per (block, kv-head): rows [...,
    block_size, n_kv, head_dim] -> (quantized rows, scales [..., n_kv]
    f32). `dtype` picks the grid — jnp.int8 (127-level integer ladder)
    or jnp.float8_e4m3fn (e4m3 float grid, block absmax mapped onto
    ±448); anything else is a loud error, never a silent fallback.
    Zero blocks get scale 1.0 (models/quant's convention), so fresh
    pools roundtrip exactly."""
    dt = jnp.dtype(dtype)
    if dt == jnp.dtype(jnp.int8):
        qt = _quantize(rows, axes=(-3, -1))
    elif dt == jnp.dtype(jnp.float8_e4m3fn):
        qt = _quantize_fp8(rows, axes=(-3, -1))
    else:
        raise ValueError(
            f"quantize_blocks: unsupported pool dtype {dt} (expected "
            "int8 or float8_e4m3fn)")
    return qt.q, jnp.squeeze(qt.s, axis=(-3, -1))


def scatter_token(pool: jax.Array, table: jax.Array, pos: jax.Array,
                  val: jax.Array) -> jax.Array:
    """Write one token row per slot into the pool.

    pool: [num_blocks, block_size, n_kv, head_dim]; table: [B,
    max_blocks]; pos: [B] int32 logical positions; val: [B, n_kv,
    head_dim]. Slot b's row lands at (table[b, pos[b]//bs],
    pos[b]%bs) — dead slots point their whole table at a reserved
    trash block, so their masked lanes scatter harmlessly."""
    bs = pool.shape[1]
    rows = jnp.arange(table.shape[0])
    bidx = table[rows, pos // bs]
    return pool.at[bidx, pos % bs].set(val)


def scatter_window(pool: jax.Array, table: jax.Array, pos0: jax.Array,
                   vals: jax.Array) -> jax.Array:
    """Write a W-token window of rows per slot into the pool.

    pool: [num_blocks, block_size, n_kv, head_dim]; table: [B,
    max_blocks]; pos0: [B] int32 first logical position per slot; vals:
    [B, W, n_kv, head_dim]. Slot b's window row i lands at
    (table[b, (pos0[b]+i)//bs], (pos0[b]+i)%bs) — the speculative
    verify scatter, where the tail of a slot's window may run past its
    mapped (or even mappable) range.

    Out-of-range positions must DROP, never clamp: a clamped table
    gather (`min(p//bs, max_blocks-1)`) lands on the row's LAST column,
    which for a fully-mapped table is a REAL block — a clamped write
    would corrupt a live logical position ~block_size tokens back. So
    positions past the table's extent are routed to block index
    `num_blocks` (one past the pool) and the scatter uses
    ``mode="drop"``."""
    nb, bs = pool.shape[0], pool.shape[1]
    b, w = vals.shape[0], vals.shape[1]
    rows = jnp.arange(b)[:, None]
    p = pos0[:, None] + jnp.arange(w)[None, :]          # [B, W]
    maxb = table.shape[1]
    bidx = table[rows, jnp.minimum(p // bs, maxb - 1)]
    bidx = jnp.where(p < maxb * bs, bidx, nb)           # OOB -> dropped
    return pool.at[bidx, p % bs].set(vals, mode="drop")


def scatter_token_q(pool_q: jax.Array, scales: jax.Array,
                    table: jax.Array, pos: jax.Array,
                    val: jax.Array):
    """`scatter_token` for quantized pools: read-modify-write the
    frontier block. pool_q int8/fp8 [num_blocks, block_size, n_kv,
    head_dim] (its dtype picks the requantization grid); scales f32
    [num_blocks, n_kv]; val [B, n_kv, head_dim] full-precision.
    Returns (pool_q, scales).

    Each slot's frontier block is gathered (B blocks, not the full
    table — bounded RMW traffic), dequantized with its old scale, the
    new row inserted, and the block requantized under its fresh absmax.
    Live slots own their frontier block exclusively (the COW guard
    forks shared blocks before the frontier reaches them), so the RMW
    never races a neighbour; dead slots all point at the trash block,
    whose duplicate writes are garbage-on-garbage.

    Out-of-range positions DROP, never clamp, for the same reason as
    `scatter_window`: both the block write and the scale write are
    routed to block index num_blocks and dropped, so an OOB row can
    neither corrupt a live block nor skew its scale."""
    nb, bs = pool_q.shape[0], pool_q.shape[1]
    maxb = table.shape[1]
    rows = jnp.arange(table.shape[0])
    bidx = table[rows, jnp.minimum(pos // bs, maxb - 1)]
    blk = pool_q[bidx]                    # [B, bs, nkv, hd] int8
    scl = scales[bidx]                    # [B, nkv]
    deq = blk.astype(jnp.float32) * scl[:, None, :, None]
    deq = deq.at[rows, pos % bs].set(val.astype(jnp.float32))
    q8, s_new = quantize_blocks(deq, pool_q.dtype)
    bidx = jnp.where(pos < maxb * bs, bidx, nb)         # OOB -> dropped
    pool_q = pool_q.at[bidx].set(q8, mode="drop")
    scales = scales.at[bidx].set(s_new, mode="drop")
    return pool_q, scales


def scatter_window_q(pool_q: jax.Array, scales: jax.Array,
                     table: jax.Array, pos0: jax.Array,
                     vals: jax.Array):
    """`scatter_window` for quantized pools: W sequential frontier
    RMWs.

    vals [B, W, n_kv, head_dim]. The window's rows land one at a time
    (a Python-unrolled W-step chain, W is static and small) because
    consecutive rows often share a block: parallel RMWs would each
    start from the ORIGINAL block and the last writer would erase its
    siblings' rows. Sequencing makes row i's RMW see rows < i — the
    quantized analog of `scatter_window`'s in-order semantics, with
    the same OOB-drop contract per row. Returns (pool_q, scales)."""
    for i in range(vals.shape[1]):
        pool_q, scales = scatter_token_q(pool_q, scales, table,
                                         pos0 + i, vals[:, i])
    return pool_q, scales


def scatter_blocks_q(pool_q: jax.Array, scales: jax.Array,
                     bids: jax.Array, rows: jax.Array):
    """`scatter_blocks` for quantized pools: whole blocks quantize in
    one shot (no RMW — the writes fully replace their targets).
    Returns (pool_q, scales)."""
    q8, s = quantize_blocks(rows, pool_q.dtype)
    return pool_q.at[bids].set(q8), scales.at[bids].set(s)


def scatter_seq_blocks_q(pool_q: jax.Array, scales: jax.Array,
                         table_row: jax.Array, rows: jax.Array):
    """`scatter_seq_blocks` for quantized pools (the chunked-prefill
    splice): every block of one sequence quantizes whole. Trash-pad
    duplicates behave exactly as in the bf16 splice — garbage blocks
    get garbage scales, gathered only under exact-zero masks. Returns
    (pool_q, scales)."""
    q8, s = quantize_blocks(rows, pool_q.dtype)
    return (pool_q.at[table_row].set(q8),
            scales.at[table_row].set(s))


def scatter_blocks(pool: jax.Array, bids: jax.Array,
                   rows: jax.Array) -> jax.Array:
    """Bulk-write whole blocks (prefill splice): bids [n] int32, rows
    [n, block_size, n_kv, head_dim]."""
    return pool.at[bids].set(rows.astype(pool.dtype))


def scatter_seq_blocks(pool: jax.Array, table_row: jax.Array,
                       rows: jax.Array) -> jax.Array:
    """Write ONE sequence's whole padded block row back into the pool
    (the chunked-prefill splice): table_row [max_blocks] int32 as
    produced by `PageTable.as_row`, rows [max_blocks, block_size,
    n_kv, head_dim] from its contiguous b=1 scratch cache.

    The row's tail entries are the server's trash-block pad, so the
    scatter carries DUPLICATE indices there; which garbage write wins
    is unspecified and irrelevant — trash rows are only ever gathered
    under an exact-zero mask. Real block ids are unique within a row
    (the allocator hands each out once), so live blocks get exactly
    their own scratch rows."""
    return pool.at[table_row].set(rows.astype(pool.dtype))


def paged_decode_attention(q: jax.Array, k_new: jax.Array,
                           v_new: jax.Array, k_pool: jax.Array,
                           v_pool: jax.Array, table: jax.Array,
                           pos: jax.Array, k_scale: jax.Array = None,
                           v_scale: jax.Array = None,
                           fused=False, interpret=None):
    """One decode step of attention over paged K/V.

    q: [B, 1, n_q, head_dim] (post-rope); k_new/v_new: [B, n_kv,
    head_dim] this step's K/V rows (post-rope — pools store post-rope
    K exactly like the dense caches); table: [B, max_blocks] int32;
    pos: [B] int32 write/attend positions. Returns (att [B, 1, n_q,
    head_dim], k_pool, v_pool) with the new rows written — write
    precedes the attention so each slot attends its own fresh token
    (the mask is `<= pos`, inclusive).

    `fused=True` routes the attention through the bitwise Pallas
    block-table kernel instead of the gather formulation;
    `fused="online"` routes through the O(block)-scratch online-softmax
    variant (tolerance-budgeted — see its numerics contract). Same
    writes either way. Quantized (int8/fp8) pools pass k_scale/v_scale
    ([num_blocks, n_kv] f32): the new rows quantize at write time
    (frontier RMW, grid picked off the pool dtype) and the return grows
    to (att, k_pool, v_pool, k_scale, v_scale)."""
    quant = k_scale is not None
    if quant:
        k_pool, k_scale = scatter_token_q(k_pool, k_scale, table, pos,
                                          k_new)
        v_pool, v_scale = scatter_token_q(v_pool, v_scale, table, pos,
                                          v_new)
    else:
        k_pool = scatter_token(k_pool, table, pos, k_new)
        v_pool = scatter_token(v_pool, table, pos, v_new)
    if fused:
        fpa = (fused_paged_online_attention if fused == "online"
               else fused_paged_attention)
        att = fpa(q, k_pool, v_pool, table, pos,
                  k_scale=k_scale, v_scale=v_scale,
                  interpret=interpret)
    else:
        kc = gather_block_kv(k_pool, table, k_scale, q.dtype)
        vc = gather_block_kv(v_pool, table, v_scale, q.dtype)
        b, _, nq, hd = q.shape
        nkv = kc.shape[2]
        g = nq // nkv
        qg = q.reshape(b, 1, nkv, g, hd)
        s = jnp.einsum("bqngh,bknh->bngqk", qg, kc) / math.sqrt(hd)
        kpos = jnp.arange(kc.shape[1])
        live = kpos[None, :] <= pos[:, None]            # [B, S]
        s = jnp.where(live[:, None, None, None, :], s, -jnp.inf)
        p = jax.nn.softmax(s.astype(jnp.float32), axis=-1
                           ).astype(q.dtype)
        att = jnp.einsum("bngqk,bknh->bqngh", p, vc).reshape(
            q.shape[0], 1, nq, hd)
    if quant:
        return att, k_pool, v_pool, k_scale, v_scale
    return att, k_pool, v_pool


def paged_window_attention(q: jax.Array, k_new: jax.Array,
                           v_new: jax.Array, k_pool: jax.Array,
                           v_pool: jax.Array, table: jax.Array,
                           pos0: jax.Array, k_scale: jax.Array = None,
                           v_scale: jax.Array = None,
                           fused=False, interpret=None):
    """W-token speculative-verify attention over paged K/V.

    q: [B, W, n_q, head_dim] (post-rope); k_new/v_new: [B, W, n_kv,
    head_dim] the window's K/V rows; table: [B, max_blocks]; pos0: [B]
    int32 first position per slot (window row i sits at pos0+i).
    Returns (att [B, W, n_q, head_dim], k_pool, v_pool) — plus the
    updated scales when k_scale/v_scale are given, exactly like
    `paged_decode_attention`; `fused=True` routes through the bitwise
    Pallas block-table kernel and `fused="online"` through the
    online-softmax variant (both share the per-window-row horizon
    mask).

    Per-query causal horizon: window row i attends positions
    `<= pos0 + i` — exactly the horizon W sequential `scatter_token` +
    `paged_decode_attention` steps would see, so the verify logits are
    byte-identical to the sequential decode the window replaces.
    Rejected draft rows stay in the pool as garbage, which is safe for
    the same write-precedes-gather reason as the dense scratch tail:
    a position is only ever attended once the frontier reaches it, and
    the frontier only advances past freshly (re)written rows. Under
    quantized pools that garbage ALSO sits under the block's absmax until
    rewritten — rejected rows can widen their block's scale, which
    costs the block's live rows at most one extra requantization
    rounding, identically on the gather and fused paths."""
    quant = k_scale is not None
    if quant:
        k_pool, k_scale = scatter_window_q(k_pool, k_scale, table,
                                           pos0, k_new)
        v_pool, v_scale = scatter_window_q(v_pool, v_scale, table,
                                           pos0, v_new)
    else:
        k_pool = scatter_window(k_pool, table, pos0, k_new)
        v_pool = scatter_window(v_pool, table, pos0, v_new)
    if fused:
        fpa = (fused_paged_online_attention if fused == "online"
               else fused_paged_attention)
        att = fpa(q, k_pool, v_pool, table, pos0,
                  k_scale=k_scale, v_scale=v_scale,
                  interpret=interpret)
    else:
        kc = gather_block_kv(k_pool, table, k_scale, q.dtype)
        vc = gather_block_kv(v_pool, table, v_scale, q.dtype)
        b, w, nq, hd = q.shape
        nkv = kc.shape[2]
        g = nq // nkv
        qg = q.reshape(b, w, nkv, g, hd)
        s = jnp.einsum("bqngh,bknh->bngqk", qg, kc) / math.sqrt(hd)
        kpos = jnp.arange(kc.shape[1])
        posw = pos0[:, None] + jnp.arange(w)[None, :]   # [B, W]
        live = kpos[None, None, :] <= posw[:, :, None]  # [B, W, S]
        s = jnp.where(live[:, None, None, :, :], s, -jnp.inf)
        p = jax.nn.softmax(s.astype(jnp.float32), axis=-1
                           ).astype(q.dtype)
        att = jnp.einsum("bngqk,bknh->bqngh", p, vc).reshape(
            b, w, nq, hd)
    if quant:
        return att, k_pool, v_pool, k_scale, v_scale
    return att, k_pool, v_pool
