"""Gather-based paged decode attention over block tables.

The device side of the `hpx_tpu/cache` subsystem: K/V for every
request lives in one preallocated per-layer pool of fixed-size blocks
(`[num_blocks, block_size, n_kv, head_dim]`), and a per-step int32
block table (`cache/page_table.py`) maps each slot's logical positions
to physical blocks. This module is pure jit-safe array plumbing — no
host state, no syncs — so the serving layer can compose it with its
projections while the numerics stay in one place.

Numerical contract: `paged_decode_attention` is element-for-element the
attention core of `models/serving._block_decode_rows` — same einsum
contractions, same contraction lengths (`max_blocks * block_size` rows
gathered in logical order == the dense `smax` rows), same -inf mask and
f32 softmax. Rows past a slot's position are masked to exact-zero
probability, so the garbage content of pad/trash blocks contributes
exactly 0.0 — paged and dense servers emit byte-identical tokens.

The gather materializes a `[B, S, n_kv, head_dim]` view per layer —
the XLA-oracle formulation. A fused Pallas kernel that walks the block
table in VMEM (the vLLM PagedAttention shape) is the follow-on once
the flash path grows a block-table BlockSpec; this module is the
equivalence oracle such a kernel will be tested against.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

__all__ = [
    "gather_block_kv",
    "paged_decode_attention",
    "paged_window_attention",
    "scatter_blocks",
    "scatter_seq_blocks",
    "scatter_token",
    "scatter_window",
]


def gather_block_kv(pool: jax.Array, table: jax.Array) -> jax.Array:
    """Materialize logical K or V rows from a block pool.

    pool: [num_blocks, block_size, n_kv, head_dim]; table: [B,
    max_blocks] int32. Returns [B, max_blocks * block_size, n_kv,
    head_dim] — slot b's logical row p at index p (pad blocks yield
    garbage rows the causal mask must exclude)."""
    g = pool[table]                       # [B, maxb, bs, nkv, hd]
    b, m, s, n, h = g.shape
    return g.reshape(b, m * s, n, h)


def scatter_token(pool: jax.Array, table: jax.Array, pos: jax.Array,
                  val: jax.Array) -> jax.Array:
    """Write one token row per slot into the pool.

    pool: [num_blocks, block_size, n_kv, head_dim]; table: [B,
    max_blocks]; pos: [B] int32 logical positions; val: [B, n_kv,
    head_dim]. Slot b's row lands at (table[b, pos[b]//bs],
    pos[b]%bs) — dead slots point their whole table at a reserved
    trash block, so their masked lanes scatter harmlessly."""
    bs = pool.shape[1]
    rows = jnp.arange(table.shape[0])
    bidx = table[rows, pos // bs]
    return pool.at[bidx, pos % bs].set(val)


def scatter_window(pool: jax.Array, table: jax.Array, pos0: jax.Array,
                   vals: jax.Array) -> jax.Array:
    """Write a W-token window of rows per slot into the pool.

    pool: [num_blocks, block_size, n_kv, head_dim]; table: [B,
    max_blocks]; pos0: [B] int32 first logical position per slot; vals:
    [B, W, n_kv, head_dim]. Slot b's window row i lands at
    (table[b, (pos0[b]+i)//bs], (pos0[b]+i)%bs) — the speculative
    verify scatter, where the tail of a slot's window may run past its
    mapped (or even mappable) range.

    Out-of-range positions must DROP, never clamp: a clamped table
    gather (`min(p//bs, max_blocks-1)`) lands on the row's LAST column,
    which for a fully-mapped table is a REAL block — a clamped write
    would corrupt a live logical position ~block_size tokens back. So
    positions past the table's extent are routed to block index
    `num_blocks` (one past the pool) and the scatter uses
    ``mode="drop"``."""
    nb, bs = pool.shape[0], pool.shape[1]
    b, w = vals.shape[0], vals.shape[1]
    rows = jnp.arange(b)[:, None]
    p = pos0[:, None] + jnp.arange(w)[None, :]          # [B, W]
    maxb = table.shape[1]
    bidx = table[rows, jnp.minimum(p // bs, maxb - 1)]
    bidx = jnp.where(p < maxb * bs, bidx, nb)           # OOB -> dropped
    return pool.at[bidx, p % bs].set(vals, mode="drop")


def scatter_blocks(pool: jax.Array, bids: jax.Array,
                   rows: jax.Array) -> jax.Array:
    """Bulk-write whole blocks (prefill splice): bids [n] int32, rows
    [n, block_size, n_kv, head_dim]."""
    return pool.at[bids].set(rows.astype(pool.dtype))


def scatter_seq_blocks(pool: jax.Array, table_row: jax.Array,
                       rows: jax.Array) -> jax.Array:
    """Write ONE sequence's whole padded block row back into the pool
    (the chunked-prefill splice): table_row [max_blocks] int32 as
    produced by `PageTable.as_row`, rows [max_blocks, block_size,
    n_kv, head_dim] from its contiguous b=1 scratch cache.

    The row's tail entries are the server's trash-block pad, so the
    scatter carries DUPLICATE indices there; which garbage write wins
    is unspecified and irrelevant — trash rows are only ever gathered
    under an exact-zero mask. Real block ids are unique within a row
    (the allocator hands each out once), so live blocks get exactly
    their own scratch rows."""
    return pool.at[table_row].set(rows.astype(pool.dtype))


def paged_decode_attention(q: jax.Array, k_new: jax.Array,
                           v_new: jax.Array, k_pool: jax.Array,
                           v_pool: jax.Array, table: jax.Array,
                           pos: jax.Array):
    """One decode step of attention over paged K/V.

    q: [B, 1, n_q, head_dim] (post-rope); k_new/v_new: [B, n_kv,
    head_dim] this step's K/V rows (post-rope — pools store post-rope
    K exactly like the dense caches); table: [B, max_blocks] int32;
    pos: [B] int32 write/attend positions. Returns (att [B, 1, n_q,
    head_dim], k_pool, v_pool) with the new rows written — write
    precedes the gather so each slot attends its own fresh token
    (the mask is `<= pos`, inclusive)."""
    k_pool = scatter_token(k_pool, table, pos, k_new)
    v_pool = scatter_token(v_pool, table, pos, v_new)
    kc = gather_block_kv(k_pool, table)
    vc = gather_block_kv(v_pool, table)
    b, _, nq, hd = q.shape
    nkv = kc.shape[2]
    g = nq // nkv
    qg = q.reshape(b, 1, nkv, g, hd)
    s = jnp.einsum("bqngh,bknh->bngqk", qg, kc) / math.sqrt(hd)
    kpos = jnp.arange(kc.shape[1])
    live = kpos[None, :] <= pos[:, None]                # [B, S]
    s = jnp.where(live[:, None, None, None, :], s, -jnp.inf)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    att = jnp.einsum("bngqk,bknh->bqngh", p, vc).reshape(b, 1, nq, hd)
    return att, k_pool, v_pool


def paged_window_attention(q: jax.Array, k_new: jax.Array,
                           v_new: jax.Array, k_pool: jax.Array,
                           v_pool: jax.Array, table: jax.Array,
                           pos0: jax.Array):
    """W-token speculative-verify attention over paged K/V.

    q: [B, W, n_q, head_dim] (post-rope); k_new/v_new: [B, W, n_kv,
    head_dim] the window's K/V rows; table: [B, max_blocks]; pos0: [B]
    int32 first position per slot (window row i sits at pos0+i).
    Returns (att [B, W, n_q, head_dim], k_pool, v_pool).

    Per-query causal horizon: window row i attends positions
    `<= pos0 + i` — exactly the horizon W sequential `scatter_token` +
    `paged_decode_attention` steps would see, so the verify logits are
    byte-identical to the sequential decode the window replaces.
    Rejected draft rows stay in the pool as garbage, which is safe for
    the same write-precedes-gather reason as the dense scratch tail:
    a position is only ever attended once the frontier reaches it, and
    the frontier only advances past freshly (re)written rows."""
    k_pool = scatter_window(k_pool, table, pos0, k_new)
    v_pool = scatter_window(v_pool, table, pos0, v_new)
    kc = gather_block_kv(k_pool, table)
    vc = gather_block_kv(v_pool, table)
    b, w, nq, hd = q.shape
    nkv = kc.shape[2]
    g = nq // nkv
    qg = q.reshape(b, w, nkv, g, hd)
    s = jnp.einsum("bqngh,bknh->bngqk", qg, kc) / math.sqrt(hd)
    kpos = jnp.arange(kc.shape[1])
    posw = pos0[:, None] + jnp.arange(w)[None, :]       # [B, W]
    live = kpos[None, None, :] <= posw[:, :, None]      # [B, W, S]
    s = jnp.where(live[:, None, None, :, :], s, -jnp.inf)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    att = jnp.einsum("bngqk,bknh->bqngh", p, vc).reshape(b, w, nq, hd)
    return att, k_pool, v_pool
