"""Version info.

Reference analog: libs/core/version (hpx::full_version_as_string).
"""

HPX_TPU_VERSION = (0, 1, 0)
__version__ = ".".join(str(v) for v in HPX_TPU_VERSION)


def full_version_as_string() -> str:
    return ".".join(str(v) for v in HPX_TPU_VERSION)
