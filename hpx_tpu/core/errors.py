"""Error codes and exceptions.

Reference analog: libs/core/errors (hpx::error enum, hpx::exception,
HPX_THROW_EXCEPTION, error_code). The TPU rebuild keeps the error taxonomy —
every runtime error carries a stable enum value usable programmatically —
but uses native Python exceptions as the carrier.
"""

from __future__ import annotations

import enum
from typing import Any, Optional


class Error(enum.IntEnum):
    """Stable error codes (subset of hpx::error relevant to this runtime)."""

    success = 0
    no_success = 1
    not_implemented = 2
    out_of_memory = 3
    bad_action_code = 4
    bad_component_type = 5
    network_error = 6
    version_too_new = 7
    version_too_old = 8
    unknown_component_address = 9
    duplicate_component_address = 10
    invalid_status = 11
    bad_parameter = 12
    internal_server_error = 13
    service_unavailable = 14
    bad_request = 15
    repeated_request = 16
    lock_error = 17
    duplicate_console = 18
    no_registered_console = 19
    startup_timed_out = 20
    uninitialized_value = 21
    bad_response_type = 22
    deadlock = 23
    assertion_failure = 24
    null_thread_id = 25
    invalid_data = 26
    yield_aborted = 27
    dynamic_link_failure = 28
    commandline_option_error = 29
    serialization_error = 30
    unhandled_exception = 31
    kernel_error = 32
    broken_task = 33
    task_moved = 34
    task_already_started = 35
    future_already_retrieved = 36
    promise_already_satisfied = 37
    future_does_not_support_cancellation = 38
    future_can_not_be_cancelled = 39
    no_state = 40
    broken_promise = 41
    thread_resource_error = 42
    future_cancelled = 43
    thread_cancelled = 44
    thread_not_interruptable = 45
    duplicate_component_id = 46
    unknown_error = 47
    bad_plugin_type = 48
    filesystem_error = 49
    bad_function_call = 50
    task_canceled_exception = 51
    task_block_not_active = 52
    out_of_range = 53
    length_error = 54
    migration_needs_retry = 55


class HpxError(RuntimeError):
    """Base runtime exception carrying an `Error` code.

    Analog of hpx::exception (libs/core/errors/include/hpx/errors/exception.hpp).
    """

    def __init__(self, code: Error, message: str = "", function: str = "",
                 file: str = "", line: int = 0):
        self.code = Error(code)
        self.function = function
        self.file = file
        self.line = line
        super().__init__(
            f"{message} (hpx error: {self.code.name}[{int(self.code)}])"
            + (f" in {function}" if function else "")
        )

    def get_error(self) -> Error:
        return self.code

    def __reduce__(self):
        # exceptions travel inside parcels: default exception pickling
        # would re-call __init__ with the FORMATTED message as the code
        # argument, which breaks on the receiving side. __dict__ rides
        # along wholesale so subclass attributes (e.g.
        # ReplayValidationError.attempts) survive the wire.
        return (_restore_hpx_error,
                (type(self), self.args[0] if self.args else ""),
                dict(self.__dict__))


def _restore_hpx_error(cls, text: str):
    e = cls.__new__(cls)
    RuntimeError.__init__(e, text)
    return e


class FutureError(HpxError):
    """std::future_error analog for future/promise protocol violations."""


class BadParameter(HpxError):
    def __init__(self, message: str = "", function: str = ""):
        super().__init__(Error.bad_parameter, message, function)


class UndeclaredConfigKey(BadParameter):
    """Strict-mode config contract: an ``hpx.``-prefixed key that is
    not in the config_schema registry at all — a typo, or a knob that
    was never declared. Fix: declare it in config_schema.py first."""


class ReservedConfigKey(BadParameter):
    """Strict-mode config contract: the key IS declared, but as
    ``reserved=True`` (HPX interface parity — accepted from ini/CLI so
    reference invocations keep working, but nothing in this runtime
    reads it). A runtime ``set()`` would be silently ignored, so
    strict mode fails it with THIS type — distinct from
    :class:`UndeclaredConfigKey` so callers can tell "typo" from
    "knob without a reader"."""


class NotImplementedYet(HpxError):
    def __init__(self, message: str = "", function: str = ""):
        super().__init__(Error.not_implemented, message, function)


class NetworkError(HpxError):
    def __init__(self, message: str = "", function: str = ""):
        super().__init__(Error.network_error, message, function)


class LocalityLost(NetworkError):
    """A peer locality is gone: the failure detector promoted it
    SUSPECT→DEAD, or a send targeted a locality already marked dead.
    Pending parcels toward it fail with THIS type (not a generic
    NetworkError) so callers can distinguish "the worker died —
    fail over" from "the wire hiccuped — retry". Lives here (not in
    `svc/faultinject`) so `dist/runtime` can raise the real thing;
    the injected variant subclasses this, keeping one except clause
    for both."""

    def __init__(self, locality: int = -1, message: str = "",
                 function: str = ""):
        super().__init__(
            message or f"locality {locality} lost", function)
        self.locality = locality


class DeadlockError(HpxError):
    def __init__(self, message: str = "", function: str = ""):
        super().__init__(Error.deadlock, message, function)


class CacheOOM(HpxError):
    """A KV block pool has no free block. Recoverable: evict
    unreferenced radix chains (`RadixCache.evict`) and retry — the
    serving loop's OOM→evict→retry path. Lives here (not in
    `cache/block_allocator`) so `svc/faultinject` can subclass it for
    injected-OOM faults without a cache↔svc import cycle."""

    def __init__(self, message: str = "", function: str = ""):
        super().__init__(Error.out_of_memory, message, function)


def throw_exception(code: Error, message: str = "", function: str = "") -> None:
    """HPX_THROW_EXCEPTION analog."""
    raise HpxError(code, message, function)


class ErrorCode:
    """hpx::error_code analog: out-parameter error reporting for the
    no-throw API variants (f(..., ec) sets ec instead of raising)."""

    def __init__(self) -> None:
        self.value: Error = Error.success
        self.message: str = ""

    def clear(self) -> None:
        self.value = Error.success
        self.message = ""

    def set(self, code: Error, message: str = "") -> None:
        self.value = Error(code)
        self.message = message

    def __bool__(self) -> bool:  # truthy when an error occurred
        return self.value != Error.success

    def __repr__(self) -> str:
        return f"ErrorCode({self.value.name}, {self.message!r})"


def throws_or_sets(ec: Optional[ErrorCode], code: Error, message: str) -> Any:
    """Helper implementing HPX's `throws` vs `error_code&` convention."""
    if ec is None:
        raise HpxError(code, message)
    ec.set(code, message)
    return None
