"""Central declaration of every ``hpx.*`` configuration key.

Reference analog: HPX's generated ini default groups in
runtime_configuration.cpp — every knob the runtime understands is
declared in one place with its type and default, so a typo'd key is a
startup error instead of a silently-ignored setting.

Each key the tree reads through ``Configuration.get*`` must be declared
here with its value type, compiled-in default (``None`` when the read
site carries its own inline default), and a one-line doc string.
``hpxlint`` rule HPX014 cross-checks this registry against every
``cfg.get*("hpx....")`` call in the tree: undeclared reads, declared
keys nothing reads, and getter/type mismatches all fail the lint gate.
``Configuration(strict=True)`` enforces the same contract at runtime.

Keys marked ``reserved=True`` exist for HPX interface parity (accepted
on the command line / ini so reference invocations keep working) but
have no reader yet; HPX014 skips them in its dead-key check.

Adding a config knob: declare it here FIRST (key, type, default, doc),
then read it via ``runtime_config().get_<type>(...)`` — in that order,
or HPX014 flags the read as undeclared and tier-1 fails.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

_VALID_TYPES = ("str", "int", "bool", "float")


@dataclasses.dataclass(frozen=True)
class Tunable:
    """Live-tuning contract for one knob (the ``tunable=`` field).

    Declaring a knob tunable asserts two things: moving it at a safe
    boundary (flush/admit tick, never mid-step) cannot change emitted
    tokens (output invariance — the sha-identity tests pin this), and
    the adaptive tuner may move it inside ``[lo, hi]`` one bounded
    ``step`` at a time.  ``geometric=True`` steps multiply/divide by
    ``step`` instead of adding/subtracting it (power-of-two ladders).
    ``compiles=True`` marks a knob whose move can mint new jit
    programs — the tuner charges such moves their measured compile
    time (svc/progprof) and only keeps them when the projected
    steady-state win amortizes it."""

    lo: int
    hi: int
    step: int = 1
    geometric: bool = False
    compiles: bool = False


@dataclasses.dataclass(frozen=True)
class ConfigKey:
    """One declared configuration knob."""

    key: str
    type: str                 # "str" | "int" | "bool" | "float"
    default: Optional[str]    # None = no compiled-in default
    doc: str
    reserved: bool = False    # HPX-parity: declared but not read (yet)
    # closed value set for enumerated str knobs (None = free-form).
    # ``Configuration(strict=True)`` rejects a set() outside it with
    # the valid set in the error — a typo'd kv_dtype=fp8_e5m2 fails at
    # the set, not as a silently-ignored setting downstream.
    choices: Optional[Tuple[str, ...]] = None
    # non-None marks the knob safe for the online tuner (svc/autotune)
    tunable: Optional[Tunable] = None


_SCHEMA: Dict[str, ConfigKey] = {}


def declare(key: str, type: str, default: Optional[str], doc: str,
            reserved: bool = False,
            choices: Optional[Tuple[str, ...]] = None,
            tunable: Optional[Tunable] = None) -> ConfigKey:
    """Register one knob; duplicate keys and unknown types are errors.
    ``choices`` declares a closed value set for enumerated str knobs
    (the declared default must be a member); ``tunable`` declares the
    knob safe for online auto-tuning with its bounds/step contract."""
    if type not in _VALID_TYPES:
        raise ValueError(f"config key {key!r}: bad type {type!r} "
                         f"(expected one of {_VALID_TYPES})")
    if key in _SCHEMA:
        raise ValueError(f"config key {key!r} declared twice")
    if choices is not None:
        choices = tuple(choices)
        if type != "str":
            raise ValueError(f"config key {key!r}: choices= is only "
                             "meaningful for str knobs")
        if default is not None and default not in choices:
            raise ValueError(f"config key {key!r}: default {default!r} "
                             f"not in choices {choices}")
    if tunable is not None:
        if type not in ("int", "str"):
            # str covers "auto"-defaulted knobs whose live values are
            # integers (radix budget); bool/float knobs have no bounded
            # step semantics the tuner understands
            raise ValueError(f"config key {key!r}: tunable= needs an "
                             "int-valued knob (type 'int' or 'str')")
        if tunable.lo > tunable.hi:
            raise ValueError(f"config key {key!r}: tunable lo "
                             f"{tunable.lo} > hi {tunable.hi}")
        if tunable.step < (2 if tunable.geometric else 1):
            raise ValueError(f"config key {key!r}: tunable step "
                             f"{tunable.step} too small")
    entry = ConfigKey(key, type, default, doc, reserved, choices,
                      tunable)
    _SCHEMA[key] = entry
    return entry


def is_declared(key: str) -> bool:
    return key in _SCHEMA


def lookup(key: str) -> Optional[ConfigKey]:
    return _SCHEMA.get(key)


def all_keys() -> Dict[str, ConfigKey]:
    """Copy of the full registry (key -> ConfigKey)."""
    return dict(_SCHEMA)


def defaults() -> Dict[str, str]:
    """The compiled-in defaults map consumed by ``config.DEFAULTS`` —
    exactly the declared keys that carry a non-None default."""
    return {k: e.default for k, e in _SCHEMA.items()
            if e.default is not None}


def tunable_keys() -> Dict[str, ConfigKey]:
    """The declared tunable subset (key -> ConfigKey) — the ONLY knobs
    the adaptive tuner may ever move."""
    return {k: e for k, e in _SCHEMA.items() if e.tunable is not None}


# ---------------------------------------------------------------------------
# Declarations. Order matches the historical config.DEFAULTS layout so
# the defaults() dict is drop-in identical; default-less keys (read
# sites carry their own inline defaults) follow, grouped by section.
# ---------------------------------------------------------------------------

# -- core / scheduling ------------------------------------------------------
declare("hpx.os_threads", "str", "auto", "host worker threads (auto = cores, floor 4)")
declare("hpx.localities", "int", "1", "number of localities in the launch")
declare("hpx.locality", "int", "0", "this process's locality id")
declare("hpx.queuing", "str", "local-priority-fifo",
        "scheduler choice", reserved=True)
declare("hpx.scheduler.native", "bool", "1",
        "use the C++ scheduler when available")
declare("hpx.stacks.small_size", "int", "0",
        "stackful-coroutine stack size (no stackful coroutines on host)",
        reserved=True)

# -- parcel layer -----------------------------------------------------------
declare("hpx.parcel.enable", "bool", "1",
        "parcel transport master switch", reserved=True)
declare("hpx.parcel.port", "int", "7910", "TCP port for the parcelport")
declare("hpx.startup_timeout", "float", "120",
        "seconds to wait for all localities at startup")
declare("hpx.parcel.address", "str", "127.0.0.1", "parcelport bind address")
declare("hpx.parcel.bootstrap", "str", "tcp",
        "bootstrap parcelport kind", reserved=True)
declare("hpx.parcel.max_message_size", "int", str(1 << 30),
        "largest admissible parcel in bytes", reserved=True)
declare("hpx.parcel.secret", "str", None,
        "shared HMAC secret for parcel authentication ('' = off)")
declare("hpx.parcel.allow_insecure", "bool", None,
        "permit unauthenticated parcels when no secret is set")
declare("hpx.parcel.bind_any", "bool", None,
        "bind the listening socket to 0.0.0.0 instead of the address")
declare("hpx.parcel.compression", "str", None,
        "wire compression codec ('' = off)")
declare("hpx.parcel.compression_min_bytes", "int", None,
        "compress only parcels at least this large")
declare("hpx.parcel.coalescing", "bool", None,
        "batch small parcels into one wire message")
declare("hpx.parcel.coalescing_count", "int", None,
        "max parcels folded into one coalesced message")
declare("hpx.parcel.coalescing_bytes", "int", None,
        "max coalesced payload bytes before an eager flush")
declare("hpx.parcel.coalescing_interval", "float", None,
        "seconds a parcel may wait in the coalescing buffer")
declare("hpx.parcel.endpoint", "str", None,
        "--hpx:hpx CLI sugar target (endpoint of locality 0)",
        reserved=True)

# -- AGAS / distributed control ---------------------------------------------
declare("hpx.agas.service_mode", "str", "bootstrap",
        "locality 0 hosts the registry", reserved=True)
declare("hpx.agas.max_pending_refcnt_requests", "int", "4096",
        "AGAS refcount request queue bound", reserved=True)
declare("hpx.agas.endpoint", "str", None,
        "--hpx:agas CLI sugar target (AGAS endpoint)", reserved=True)
declare("hpx.connect", "bool", None,
        "late-join this process to a running cluster")
declare("hpx.route_timeout", "float", None,
        "seconds an AGAS-routed parcel may wait for resolution")
declare("hpx.barrier_timeout", "float", None,
        "seconds a distributed barrier waits before failing")
declare("hpx.shutdown_timeout", "float", None,
        "seconds finalize waits for remote localities")
declare("hpx.ignore_batch_env", "bool", None,
        "--hpx:ignore-batch-env CLI sugar (consumed at config init)",
        reserved=True)
declare("hpx.dist.heartbeat_interval", "float", None,
        "seconds between liveness heartbeats (0 = off)")
declare("hpx.dist.heartbeat_suspect", "float", None,
        "missed-heartbeat seconds before a locality is suspect")
declare("hpx.dist.heartbeat_dead", "float", None,
        "missed-heartbeat seconds before a locality is declared dead")
declare("hpx.dist.idem_table_max", "int", None,
        "bounded idempotency table size for resilient actions")

# -- logging / diagnostics --------------------------------------------------
declare("hpx.logging.level", "str", "warning", "minimum logged severity")
declare("hpx.logging.destination", "str", "stderr", "log sink")
declare("hpx.diagnostics.dump_config", "bool", "0",
        "print the resolved configuration to stderr at runtime init")

# -- TPU backend ------------------------------------------------------------
declare("hpx.tpu.platform", "str", "auto", "auto | tpu | cpu", reserved=True)
declare("hpx.tpu.default_dtype", "str", "float32",
        "default device array dtype", reserved=True)
declare("hpx.tpu.donate_buffers", "bool", "1",
        "donate input buffers to XLA where safe", reserved=True)
declare("hpx.tpu.watcher_threads", "int", "2",
        "future-completion watcher pool width")
declare("hpx.tpu.eager_futures", "bool", "1",
        "device futures ready at dispatch")

# -- performance counters ---------------------------------------------------
declare("hpx.counters.enable", "bool", "1",
        "performance-counter registry master switch", reserved=True)
declare("hpx.counters.print", "str", None,
        "csv counter name patterns printed at finalize "
        "(--hpx:print-counter)")
declare("hpx.counters.print_interval", "float", None,
        "seconds between periodic counter prints (0 = finalize only)")

# -- KV cache ---------------------------------------------------------------
declare("hpx.cache.block_size", "str", "auto",
        "KV tokens per paged block (auto: HPX_PAGED_BLOCK env, then the "
        "table banked by benchmarks/flash_tune.py --paged, then 16)")
declare("hpx.cache.num_blocks", "str", "auto",
        "pool size (auto: 2x worst case)")
declare("hpx.cache.radix_budget_blocks", "str", "auto",
        "prefix-tree HBM budget",
        tunable=Tunable(lo=8, hi=1 << 20, step=2, geometric=True))
declare("hpx.cache.prefix_reuse", "bool", "1",
        "radix prefix matching on admit")
declare("hpx.cache.kv_dtype", "str", "bf16",
        "paged pool storage: bf16 (compute dtype) | int8 (absmax-scaled "
        "integer blocks) | fp8 (e4m3 blocks, same f32 scale sidecars — "
        "~0.25x decode bytes/token vs an f32 compute dtype)",
        choices=("bf16", "int8", "fp8"))
declare("hpx.cache.tier.enable", "bool", "0",
        "host-RAM KV tier: radix evictions demote block rows (raw "
        "quantized bytes + scale sidecars) to pooled host buffers "
        "instead of dropping them")
declare("hpx.cache.tier.host_budget_mb", "int", "256",
        "host tier byte budget; LRU-to-oblivion past it",
        tunable=Tunable(lo=1, hi=1 << 20, step=2, geometric=True))
declare("hpx.cache.tier.min_speedup", "float", "1.0",
        "promote only when estimated re-prefill time exceeds restore "
        "time by this factor")
declare("hpx.cache.tier.probe_mb", "int", "4",
        "host->device bandwidth probe transfer size")
declare("hpx.cache.tier.prefill_cost_us", "float", "50.0",
        "fallback per-token prefill cost when progprof has no live "
        "pg_chunk/cb_chunk samples yet")
declare("hpx.cache.tier.restore_overhead_us", "float", "200.0",
        "fixed per-promotion overhead added to the copy-time estimate "
        "(framing, checksum, splice dispatch)")

# -- serving ----------------------------------------------------------------
declare("hpx.serving.paged_kernel", "str", "auto",
        "decode-attention formulation: auto (fused on TPU, gather "
        "elsewhere) | gather (XLA oracle) | fused (bitwise Pallas "
        "block-table walk, O(S) VMEM scratch) | fused_online "
        "(flash-style online softmax, O(block) scratch — "
        "tolerance-budgeted vs the oracle, VMEM no longer bounds smax)",
        choices=("auto", "gather", "fused", "fused_online"))
declare("hpx.serving.prefill_chunk", "int", "128",
        "prompt tokens per prefill chunk",
        tunable=Tunable(lo=16, hi=1024, step=2, geometric=True,
                        compiles=True))
declare("hpx.serving.prefill_buckets", "str", "auto",
        "chunk-width ladder (csv|auto)")
declare("hpx.serving.async_dispatch", "bool", "1",
        "decode without per-step sync")
declare("hpx.serving.max_async_steps", "int", "32",
        "buffered steps before a sync",
        tunable=Tunable(lo=1, hi=256, step=2, geometric=True))
declare("hpx.serving.spec.enable", "bool", "0",
        "speculative decode in serving")
declare("hpx.serving.spec.k", "int", "4", "draft tokens per slot per step",
        tunable=Tunable(lo=1, hi=16, step=1))
declare("hpx.serving.spec.draft", "str", "prompt",
        "draft source: prompt | model")
declare("hpx.serving.spec.ngram", "int", "3",
        "max n-gram for prompt lookup")
declare("hpx.serving.spec.min_accept", "float", "0.3",
        "adaptive-k backoff threshold")
declare("hpx.serving.spec.adapt", "bool", "1",
        "per-slot adaptive k on/off")
declare("hpx.serving.spec.max_verify_faults", "int", "2",
        "verify faults before speculation self-disables")
declare("hpx.serving.ckpt_every", "int", "16",
        "tokens between slot checkpoints",
        tunable=Tunable(lo=4, hi=256, step=2, geometric=True))
declare("hpx.serving.step_retries", "int", "4",
        "step attempts before shedding")
declare("hpx.serving.retry_backoff_s", "float", "0.005",
        "base step-retry backoff")
declare("hpx.serving.admit_retries", "int", "8",
        "admit OOM deferrals before shed")
declare("hpx.serving.default_deadline_s", "float", "0",
        "per-request deadline (0=none)")
declare("hpx.serving.disagg.max_queue", "int", None,
        "disaggregated router: bound on queued prefill jobs",
        tunable=Tunable(lo=4, hi=1024, step=2, geometric=True))
declare("hpx.serving.disagg.pump_steps", "int", None,
        "decode steps per disagg pump iteration")
declare("hpx.serving.disagg.prefill_jobs", "int", None,
        "concurrent prefill jobs per prefill worker")
declare("hpx.serving.disagg.xfer_retries", "int", None,
        "KV transfer attempts before failing over")
declare("hpx.serving.moe.capacity_factor", "int", "0",
        "MoE decode expert capacity factor as an integer PERCENT "
        "(100 = GShard cf 1.0; C = ceil(T*k*pct/100 / E)); 0 = auto = "
        "drop-free (cf = n_experts), the token-identity default. "
        "Lower trades overflow drops for smaller expert exchanges",
        tunable=Tunable(lo=100, hi=6400, step=2, geometric=True,
                        compiles=True))
declare("hpx.serving.mesh.paged", "bool", "1",
        "sharded paged serving (0 restores the single-device refusal)")
declare("hpx.serving.mesh.table_residency", "str", "sharded",
        "device block-table placement on mesh: sharded | replicated")
declare("hpx.serving.fleet.prefill_workers", "int", "2",
        "fleet: prefill workers stood up by default")
declare("hpx.serving.fleet.decode_workers", "int", "2",
        "fleet: decode workers stood up at construction")
declare("hpx.serving.fleet.decode_pool_min", "int", "1",
        "fleet: autoscale floor on decode workers")
declare("hpx.serving.fleet.decode_pool_max", "int", "4",
        "fleet: autoscale ceiling on decode workers")
declare("hpx.serving.fleet.digest_entries", "int", "64",
        "fleet: prefix-digest entries pulled per decode worker")
declare("hpx.serving.fleet.digest_refresh_s", "float", "0.25",
        "fleet: seconds a pulled prefix digest stays fresh")
declare("hpx.serving.fleet.placement", "str", "prefix",
        "fleet decode placement policy", choices=("prefix", "load"))
declare("hpx.serving.fleet.w_prefix", "float", "1.0",
        "fleet placement: score weight per digest-matched block")
declare("hpx.serving.fleet.w_pressure", "float", "0.05",
        "fleet placement: score penalty per eviction/s of pressure")
declare("hpx.serving.fleet.w_tier", "float", "0.25",
        "fleet placement: discount on w_prefix for blocks a worker "
        "holds only in its host tier (cold but restorable)")
declare("hpx.serving.fleet.scale_high", "int", "8",
        "fleet autoscale: queue depth that spins a decode worker up")
declare("hpx.serving.fleet.scale_low", "int", "0",
        "fleet autoscale: queue depth that drains a decode worker")
declare("hpx.serving.fleet.idle_ticks", "int", "16",
        "fleet autoscale: consecutive idle router ticks before an "
        "idle decode worker drains")

# -- fault injection --------------------------------------------------------
declare("hpx.fault.enable", "bool", "0", "svc/faultinject master switch")
declare("hpx.fault.seed", "int", "0", "rate-mode RNG seed")
declare("hpx.fault.rate", "float", "0.0", "per-check fault probability")
declare("hpx.fault.sites", "str", "", "csv armed sites ('' = all)")
declare("hpx.fault.max", "int", "0", "total fault cap (0 = unlimited)")
declare("hpx.fault.schedule", "str", "", "csv 'site:nth' exact schedule")
declare("hpx.fault.parcel_delay_s", "float", None,
        "injected parcel delivery delay for chaos runs")

# -- tracing ----------------------------------------------------------------
declare("hpx.trace.enabled", "bool", "0", "svc/tracing off by default")
declare("hpx.trace.buffer_events", "int", "65536",
        "ring capacity (drop-oldest)")
declare("hpx.trace.counter_interval", "float", "0.05",
        "s between counter samples")
declare("hpx.trace.counters", "str", "/serving*,/cache*,/threads*,/programs*",
        "csv counter patterns sampled into the trace")

# -- metrics (svc/metrics histograms + timelines) ---------------------------
declare("hpx.metrics.hist_lo", "float", "1e-6",
        "latency histogram lowest bucket bound, seconds (values below "
        "land in the underflow bucket)")
declare("hpx.metrics.hist_hi", "float", "1e4",
        "latency histogram highest bucket bound, seconds")
declare("hpx.metrics.hist_subbuckets", "int", "8",
        "histogram buckets per octave (gamma = 2**(1/n); 8 bounds "
        "quantile relative error at ~4.4%)")
declare("hpx.metrics.quantiles", "str", "0.5,0.95,0.99",
        "csv quantiles derived as .../pNN counters per histogram")
declare("hpx.metrics.timeline_capacity", "int", "1024",
        "rids retained per RequestTimeline (drop-oldest)")

# -- program profiler (svc/progprof) ----------------------------------------
declare("hpx.prof.programs", "bool", "0",
        "per-program continuous profiler: wrap every cached_program() "
        "build in a timing/cost-accounting proxy")
declare("hpx.prof.cost_analysis", "bool", "1",
        "query XLA cost analysis (FLOPs / bytes accessed) on first call "
        "of each profiled program")
declare("hpx.prof.peak_gflops", "float", "0",
        "roofline denominator in GFLOP/s (0 = infer from device kind; "
        "unknown kinds report roofline fraction 0)")

# -- persistent perf database (svc/perfdb) ----------------------------------
declare("hpx.perfdb.path", "str", "",
        "versioned cross-run performance store (JSON); empty = no "
        "store — producers no-op, consumers fall back to constants")
declare("hpx.perfdb.use_learned_ladders", "bool", "0",
        "boot-time consult of the perfdb ladders: on a key hit with "
        "enough samples the server overrides the hand-picked serving "
        "ladder defaults; off (or on a miss) it is byte-identical to "
        "the constants")
declare("hpx.perfdb.min_samples", "int", "3",
        "samples a learned ladder/block entry needs before a cold "
        "boot trusts it (below = counted stale, constants win)")
declare("hpx.perfdb.record", "bool", "0",
        "bank the live progprof table into the perfdb on "
        "stop_profiling() (needs hpx.perfdb.path)")
declare("hpx.perfdb.allow_session", "bool", "0",
        "accept builder-session-provenance ladders at boot (default "
        "off: only on-chip-derived ladders override constants — same "
        "discipline as bench.py medians)")

# -- flight recorder (svc/flight) -------------------------------------------
declare("hpx.flight.enabled", "bool", "1",
        "fault flight recorder master switch (lazy: allocates nothing "
        "until a fault capture fires)")
declare("hpx.flight.dir", "str", "auto",
        "directory for flight bundles (auto = <tmpdir>/hpx_tpu_flight)")
declare("hpx.flight.max_bundles", "int", "8",
        "bundles retained on disk (oldest pruned first)")
declare("hpx.flight.spans", "int", "256",
        "last-N trace spans captured into each bundle")

# -- adaptive tuner (svc/autotune) ------------------------------------------
declare("hpx.tune.enable", "bool", "0",
        "closed-loop auto-tuning of the tunable serving knobs (off by "
        "default: enabling it must be an operator decision)")
declare("hpx.tune.interval_ticks", "int", "32",
        "flush ticks between tuner evaluations (tick-counted, not "
        "wall-clock, so decisions replay deterministically)")
declare("hpx.tune.w_tokens", "float", "1.0",
        "objective weight on decayed decode tokens/s")
declare("hpx.tune.w_stall", "float", "100.0",
        "objective weight on the decode-stall p99 (seconds) delta")
declare("hpx.tune.w_queue", "float", "0.05",
        "objective weight on admission queue depth")
declare("hpx.tune.hysteresis_pct", "float", "5",
        "relative objective improvement (percent) a probe must show "
        "before its knob move is kept (anti-thrash band)")
declare("hpx.tune.cooldown_ticks", "int", "2",
        "evaluation intervals a knob is held after a reverted probe")
declare("hpx.tune.freeze", "str", "",
        "csv knob names the tuner must never move")
declare("hpx.tune.compile_amortize_s", "float", "30",
        "amortization horizon: a compile-minting move is kept only if "
        "its projected win over this many seconds covers the measured "
        "compile cost")
declare("hpx.tune.seed", "int", "0",
        "deterministic probe-order seed (rotates the round-robin "
        "starting knob)")

# -- live observability (svc/exemplars, svc/slo_alerts, svc/opsplane) ------
declare("hpx.obs.port", "int", "-1",
        "ops-plane HTTP port (/varz /statusz /tracez /flightz /healthz); "
        "-1 = off, 0 = ephemeral OS-assigned, >0 = fixed")
declare("hpx.obs.host", "str", "127.0.0.1",
        "ops-plane bind address (loopback by default: the endpoint is "
        "an operator surface, not a public one)")
declare("hpx.obs.exemplars", "bool", "0",
        "capture tail-bucket exemplars (rid, value, wall ts, span ref) "
        "on the SLO latency histograms")
declare("hpx.obs.exemplars_per_bucket", "int", "4",
        "exemplar reservoir slots per histogram bucket (deterministic "
        "ring replacement: slot = offers-to-bucket mod capacity)")
declare("hpx.obs.exemplar_quantile", "float", "0.95",
        "only records landing at/above this quantile's bucket capture "
        "an exemplar (the tail is what needs attribution)")
declare("hpx.obs.exemplar_refresh", "int", "64",
        "offers between threshold-bucket recomputes (amortizes the "
        "O(buckets) cumulative scan off the record path)")
declare("hpx.obs.alerts", "bool", "0",
        "SLO burn-rate alert evaluation at the serving flush boundary "
        "(off by default: zero-overhead is-None fast path)")
declare("hpx.obs.alert_rules", "str", "",
        "csv 'hist:threshold_s:target' SLO rules ('' = built-in "
        "defaults, see svc/slo_alerts.DEFAULT_RULES)")
declare("hpx.obs.alert_fast_s", "float", "300",
        "fast burn-rate window, seconds (SRE 5m page window)")
declare("hpx.obs.alert_slow_s", "float", "3600",
        "slow burn-rate window, seconds (gates flapping: both windows "
        "must burn before an alert fires)")
declare("hpx.obs.alert_burn_fast", "float", "14.4",
        "burn-rate factor the fast window must exceed (14.4 = a 30d "
        "budget gone in 2d)")
declare("hpx.obs.alert_burn_slow", "float", "6",
        "burn-rate factor the slow window must exceed")
declare("hpx.obs.alert_interval_s", "float", "1.0",
        "minimum wall seconds between alert evaluations (the flush "
        "boundary can tick far faster than SLO state moves)")
declare("hpx.obs.alert_trace_dump", "bool", "0",
        "dump the live trace ring next to the flight bundle when an "
        "alert fires")

# -- checkpoint / resiliency / exec -----------------------------------------
declare("hpx.checkpoint.dir", "str", "./checkpoints",
        "base directory for checkpoint_path() relative names")
declare("hpx.resiliency.replay_default_n", "int", "3",
        "replay attempts when callers pass n=None")
declare("hpx.exec.default_chunk", "str", "auto",
        "default chunker: auto | static[:N] | dynamic[:N] | guided | N")
declare("hpx.exec.min_chunk_size", "int", "1",
        "floor on per-chunk iterations for auto/guided chunking")
