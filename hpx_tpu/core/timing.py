"""Timing utilities — hpx::chrono analogs.

Reference analog: libs/core/timing (`hpx::chrono::high_resolution_timer`,
`high_resolution_clock`) and libs/core/timed_execution (sleep on HPX
threads, timed executors — SURVEY.md §2.1).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional

from ..futures.future import Future, SharedState

__all__ = [
    "HighResolutionTimer", "high_resolution_clock_now", "sleep_for",
    "sleep_until", "async_after", "async_at", "TimedExecutor",
]


class HighResolutionTimer:
    """hpx::chrono::high_resolution_timer: elapsed seconds since
    construction or last restart()."""

    __slots__ = ("_t0",)

    def __init__(self, start: bool = True) -> None:
        self._t0 = time.perf_counter() if start else None

    def start(self) -> None:
        self._t0 = time.perf_counter()

    restart = start

    def elapsed(self) -> float:
        if self._t0 is None:
            self.start()
            return 0.0
        return time.perf_counter() - self._t0

    def elapsed_microseconds(self) -> int:
        return int(self.elapsed() * 1e6)

    def elapsed_nanoseconds(self) -> int:
        return int(self.elapsed() * 1e9)


def high_resolution_clock_now() -> int:
    """hpx::chrono::high_resolution_clock::now() in nanoseconds."""
    return time.perf_counter_ns()


def sleep_for(seconds: float) -> None:
    """hpx::this_thread::sleep_for. Plain time.sleep releases the GIL,
    so other pool workers keep running — but it DOES occupy this worker
    (no stackful suspension in Python); prefer async_after for
    fire-later work."""
    time.sleep(max(0.0, seconds))


def sleep_until(deadline: float) -> None:
    """Sleep until a time.monotonic() deadline."""
    sleep_for(deadline - time.monotonic())


_timer_thread: Optional[threading.Thread] = None
_timer_cv = threading.Condition()
_timer_heap: list = []   # (fire_at_monotonic, seq, SharedState, fn, args)
_timer_seq = [0]


def _timer_loop() -> None:
    import heapq
    while True:
        with _timer_cv:
            while not _timer_heap:
                _timer_cv.wait()
            fire_at = _timer_heap[0][0]
            now = time.monotonic()
            if fire_at > now:
                _timer_cv.wait(fire_at - now)
                continue
            item = heapq.heappop(_timer_heap)
        _fire_at, _seq, st, fn, args = item
        from ..runtime.threadpool import default_pool

        def run(st=st, fn=fn, args=args) -> None:
            try:
                st.set_value(fn(*args))
            except BaseException as e:  # noqa: BLE001
                st.set_exception(e)
        default_pool().submit(run)


def _ensure_timer_thread() -> None:
    global _timer_thread
    if _timer_thread is None or not _timer_thread.is_alive():
        _timer_thread = threading.Thread(target=_timer_loop,
                                         name="hpx-timer", daemon=True)
        _timer_thread.start()
        # surface it in the io_service registry ("timer" helper pool,
        # SURVEY.md §2.1) so io_pool_names()/counters reflect reality
        try:
            from ..runtime.io_service import register_external_pool
            register_external_pool("timer", 1,
                                   "core/timing deadline thread")
        except Exception:  # noqa: BLE001 — observability only
            pass


def async_at(deadline_monotonic: float, fn: Callable[..., Any],
             *args: Any) -> Future:
    """Schedule fn at a time.monotonic() deadline → future (the
    reference's timed executors: async_execute_at)."""
    import heapq
    st = SharedState()
    _ensure_timer_thread()
    with _timer_cv:
        _timer_seq[0] += 1
        heapq.heappush(_timer_heap,
                       (deadline_monotonic, _timer_seq[0], st, fn, args))
        _timer_cv.notify_all()
    return Future(st)


def async_after(delay_seconds: float, fn: Callable[..., Any],
                *args: Any) -> Future:
    """Schedule fn after a delay → future (async_execute_after)."""
    return async_at(time.monotonic() + max(0.0, delay_seconds), fn, *args)


class TimedExecutor:
    """Timed-execution wrapper for any executor (libs/core/
    timed_execution): adds *_at / *_after spellings."""

    def __init__(self, executor: Any = None) -> None:
        if executor is None:
            from ..exec.executors import ParallelExecutor
            executor = ParallelExecutor()
        self.executor = executor

    def async_execute_after(self, delay: float, fn: Callable[..., Any],
                            *args: Any, **kwargs: Any) -> Future:
        st = SharedState()

        def hop() -> None:
            f = self.executor.async_execute(fn, *args, **kwargs)

            def forward(g: Future) -> None:
                try:
                    st.set_value(g.get())
                except BaseException as e:  # noqa: BLE001
                    st.set_exception(e)

            # hpxlint: disable=HPX003 — forward() is the sink: it routes
            # value/exception into st; the then-future is unused by design
            f.then(forward)

        async_after(delay, hop)
        return Future(st)

    def async_execute_at(self, deadline: float, fn: Callable[..., Any],
                         *args: Any, **kwargs: Any) -> Future:
        return self.async_execute_after(
            deadline - time.monotonic(), fn, *args, **kwargs)

    def post_after(self, delay: float, fn: Callable[..., Any],
                   *args: Any, **kwargs: Any) -> None:
        async_after(delay, lambda: self.executor.post(fn, *args, **kwargs))
