"""Layered runtime configuration.

Reference analog: libs/core/ini (section.key ini model),
libs/core/runtime_configuration (the merged config object every subsystem
reads), libs/full/command_line_handling (--hpx:* CLI overlay).

Merge order (later wins), mirroring HPX:
  1. compiled-in defaults (DEFAULTS below)
  2. ini files:  ./hpx_tpu.ini, $HPX_TPU_INI
  3. environment variables:  HPX_TPU_<SECTION>__<KEY>=value
     (double underscore separates section path from key; single underscores
      inside section names map to dots: HPX_TPU_PARCEL__PORT -> hpx.parcel.port)
  4. command line:  --hpx:ini=section.key=value plus sugar flags
     (--hpx:threads=N, --hpx:localities=N, --hpx:queuing=..., ...)
  5. programmatic overrides via Configuration.set()

Every subsystem reads one resolved `Configuration` object — same discipline
as HPX's runtime_configuration.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, Iterable, List, Mapping, Optional, Tuple

from . import config_schema
from .errors import BadParameter, ReservedConfigKey, UndeclaredConfigKey

# Compiled-in defaults (HPX: generated defaults in runtime_configuration.cpp).
# Sourced from the central key registry — every key, its type, default and
# doc string live in config_schema.py; hpxlint HPX014 keeps the registry
# and the tree's cfg.get*() read sites in sync.
DEFAULTS: Dict[str, str] = config_schema.defaults()


def _parse_ini_text(text: str) -> Dict[str, str]:
    """Parse `[section]\nkey = value` ini text into flat dotted keys."""
    out: Dict[str, str] = {}
    section = ""
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith((";", "#", "//")):
            continue
        if line.startswith("[") and line.endswith("]"):
            section = line[1:-1].strip()
            continue
        if "=" not in line:
            raise BadParameter(f"malformed ini line: {raw!r}", "config")
        key, _, value = line.partition("=")
        full = f"{section}.{key.strip()}" if section else key.strip()
        out[full] = value.strip()
    return out


def _env_overlay(environ: Mapping[str, str]) -> Dict[str, str]:
    out: Dict[str, str] = {}
    prefix = "HPX_TPU_"
    for name, value in environ.items():
        if not name.startswith(prefix) or name == "HPX_TPU_INI":
            continue
        rest = name[len(prefix):]
        if "__" in rest:
            section, _, key = rest.partition("__")
            dotted = "hpx." + section.lower().replace("_", ".") + "." + key.lower()
        else:
            dotted = "hpx." + rest.lower()
        out[dotted] = value
    return out


def _cli_overlay(argv: Iterable[str]) -> Tuple[Dict[str, str], List[str]]:
    """Extract --hpx:* flags; return (overrides, remaining argv).

    Sugar flags mirror HPX's CLI (libs/full/command_line_handling):
      --hpx:threads=N       -> hpx.os_threads
      --hpx:localities=N    -> hpx.localities
      --hpx:queuing=NAME    -> hpx.queuing
      --hpx:ini=sec.key=v   -> raw override
      --hpx:print-counter=X -> hpx.counters.print (comma list)
      --hpx:dump-config     -> hpx.diagnostics.dump_config=1
    """
    sugar = {
        "threads": "hpx.os_threads",
        "localities": "hpx.localities",
        "locality": "hpx.locality",
        "queuing": "hpx.queuing",
        "hpx": "hpx.parcel.endpoint",
        "agas": "hpx.agas.endpoint",
    }
    overrides: Dict[str, str] = {}
    remaining: List[str] = []
    for arg in argv:
        if not arg.startswith("--hpx:"):
            remaining.append(arg)
            continue
        body = arg[len("--hpx:"):]
        key, sep, value = body.partition("=")
        if key == "ini":
            k, _, v = value.partition("=")
            overrides[k.strip()] = v.strip()
        elif key == "dump-config":
            overrides["hpx.diagnostics.dump_config"] = "1"
        elif key == "ignore-batch-env":
            overrides["hpx.ignore_batch_env"] = "1"   # handled at init
        elif key == "print-counter":
            prev = overrides.get("hpx.counters.print", "")
            overrides["hpx.counters.print"] = (prev + "," + value) if prev else value
        elif key == "print-counter-interval":
            overrides["hpx.counters.print_interval"] = value
        elif key in sugar:
            if not sep:
                raise BadParameter(
                    f"--hpx:{key} requires a value: --hpx:{key}=VALUE", "config")
            overrides[sugar[key]] = value
        else:
            raise BadParameter(f"unknown --hpx: option: {arg}", "config")
    return overrides, remaining


class Configuration:
    """The resolved, layered configuration object (thread-safe).

    ``strict=True`` turns the config_schema registry into a runtime
    contract: reading or setting an undeclared ``hpx.``-prefixed key
    raises BadParameter instead of silently answering the default —
    the runtime twin of hpxlint HPX014's static check — and setting an
    enumerated str knob (one declared with ``choices=``) to a value
    outside its valid set raises with that set spelled out (a typo'd
    ``hpx.cache.kv_dtype=fp8_e5m2`` fails at the set() instead of
    surfacing as a downstream serving error). Keys outside the
    ``hpx.`` namespace are never policed (application-private)."""

    def __init__(self,
                 argv: Optional[Iterable[str]] = None,
                 overrides: Optional[Mapping[str, Any]] = None,
                 environ: Optional[Mapping[str, str]] = None,
                 ini_files: Optional[Iterable[str]] = None,
                 strict: bool = False):
        env = os.environ if environ is None else environ
        if argv is not None:
            argv = list(argv)     # may be a generator; we scan it twice
        self._lock = threading.Lock()
        self._strict = bool(strict)
        # monotonically bumped by every set(): long-lived readers (a
        # live ContinuousServer) cache it and re-read their knobs at
        # the next safe boundary when it moved — cheap change
        # detection without re-reading every key every step
        self._gen = 0
        self._data: Dict[str, str] = dict(DEFAULTS)

        # batch scheduler layer (above compiled defaults, below ini/env/
        # CLI): srun/mpirun/TPU-pod launches discover localities without
        # flags, as the reference does (libs/core/batch_environments).
        # Opt out with --hpx:ignore-batch-env / HPX_TPU_IGNORE_BATCH_ENV
        # (the reference's --hpx:ignore-batch-env).
        ignore_batch = env.get("HPX_TPU_IGNORE_BATCH_ENV", "") not in ("", "0")
        if argv is not None and "--hpx:ignore-batch-env" in argv:
            ignore_batch = True
        if not ignore_batch:
            from ..runtime.batch_environments import detect as _batch_detect
            batch = _batch_detect(env)
            if batch.found():
                self._data.update(batch.config_overrides())

        files = list(ini_files) if ini_files is not None else []
        if ini_files is None:
            if os.path.exists("hpx_tpu.ini"):
                files.append("hpx_tpu.ini")
            extra = env.get("HPX_TPU_INI")
            if extra:
                if not os.path.exists(extra):
                    raise BadParameter(
                        f"HPX_TPU_INI points at nonexistent file: {extra}",
                        "config")
                files.append(extra)
        for path in files:
            with open(path, "r", encoding="utf-8") as fh:
                self._data.update(_parse_ini_text(fh.read()))

        self._data.update(_env_overlay(env))

        self.remaining_argv: List[str] = []
        if argv is not None:
            cli, self.remaining_argv = _cli_overlay(argv)
            self._data.update(cli)

        if overrides:
            for k, v in overrides.items():
                self._data[str(k)] = str(v)

    def _check_declared(self, key: str) -> None:
        if (self._strict and key.startswith("hpx.")
                and not config_schema.is_declared(key)):
            raise UndeclaredConfigKey(
                f"undeclared config key {key!r} (strict mode): declare it "
                "in hpx_tpu/core/config_schema.py first", "config")

    def _check_settable(self, key: str) -> None:
        """Strict mode: a ``set()`` of a declared-but-reserved key
        fails with a RESERVED-specific type — the key exists only for
        HPX interface parity (no reader), so the write would be
        silently ignored; that is a different mistake from a typo'd
        key and gets a different error. Reserved keys still flow in
        from ini/CLI layers (reference invocations keep working) —
        only runtime set() is policed."""
        if not (self._strict and key.startswith("hpx.")):
            return
        entry = config_schema.lookup(key)
        if entry is not None and entry.reserved:
            raise ReservedConfigKey(
                f"config key {key!r} is declared reserved=True (HPX "
                "parity, no runtime reader): a set() would be silently "
                "ignored. Wire a reader and drop the reserved flag in "
                "hpx_tpu/core/config_schema.py to make it settable",
                "config")

    def _check_value(self, key: str, value: str) -> None:
        """Strict mode: enumerated str knobs (declared with choices=)
        only accept their valid set."""
        if not (self._strict and key.startswith("hpx.")):
            return
        entry = config_schema.lookup(key)
        if (entry is not None and entry.choices is not None
                and value not in entry.choices):
            raise BadParameter(
                f"{key}={value!r} is not a valid value (strict mode); "
                f"expected one of {list(entry.choices)}", "config")

    # -- queries ------------------------------------------------------------
    def get(self, key: str, default: Optional[str] = None) -> Optional[str]:
        self._check_declared(key)
        with self._lock:
            return self._data.get(key, default)

    def get_int(self, key: str, default: int = 0) -> int:
        v = self.get(key)
        if v is None or v == "auto":
            return default
        return int(v)

    def get_bool(self, key: str, default: bool = False) -> bool:
        v = self.get(key)
        if v is None:
            return default
        return v.strip().lower() in ("1", "true", "yes", "on")

    def get_float(self, key: str, default: float = 0.0) -> float:
        v = self.get(key)
        if v is None or v == "auto":
            return default
        try:
            return float(v)
        except ValueError as e:
            raise BadParameter(f"{key}={v!r} is not a float", "config") from e

    def set(self, key: str, value: Any) -> None:
        self._check_declared(str(key))
        self._check_settable(str(key))
        self._check_value(str(key), str(value))
        with self._lock:
            self._data[str(key)] = str(value)
            self._gen += 1

    def generation(self) -> int:
        """Change counter: bumped by every set(). A live server caches
        this and re-reads its tunable knobs at the next flush boundary
        when it moved (see ContinuousServer._reload_knobs)."""
        with self._lock:
            return self._gen

    def section(self, prefix: str) -> Dict[str, str]:
        """All keys under `prefix.` with the prefix stripped."""
        p = prefix.rstrip(".") + "."
        with self._lock:
            return {k[len(p):]: v for k, v in self._data.items() if k.startswith(p)}

    def dump(self) -> str:
        """--hpx:dump-config analog."""
        with self._lock:
            return "\n".join(f"{k} = {v}" for k, v in sorted(self._data.items()))

    def os_threads(self) -> int:
        """Host pool width. Unlike the reference (one OS thread per core
        running compute), our pool threads ORCHESTRATE — they block on
        futures/actions/device fences while XLA does the compute — so
        'auto' floors at 4: on a 1-core sandbox a single thread would
        let any blocking task starve the whole control plane."""
        v = self.get("hpx.os_threads", "auto")
        if v == "auto":
            return max(4, os.cpu_count() or 1)
        return max(1, int(v))


# -- process-wide resolved configuration ------------------------------------
# "Every subsystem reads one resolved config object" (HPX
# runtime_configuration discipline): subsystems call runtime_config()
# instead of constructing fresh Configurations (which would re-read ini
# files/environ and could observe divergent state mid-run).
_runtime_config: Optional[Configuration] = None
_runtime_config_lock = threading.Lock()


def runtime_config() -> Configuration:
    global _runtime_config
    if _runtime_config is None:
        with _runtime_config_lock:
            if _runtime_config is None:
                _runtime_config = Configuration()
    return _runtime_config


def set_runtime_config(cfg: Optional[Configuration]) -> None:
    """Install (or with None, reset) the process-wide configuration —
    used by runtime init with CLI argv, and by tests."""
    global _runtime_config
    with _runtime_config_lock:
        _runtime_config = cfg
