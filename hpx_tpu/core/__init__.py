from . import config, errors, version  # noqa: F401
from .config import Configuration  # noqa: F401
from .errors import (  # noqa: F401
    BadParameter,
    DeadlockError,
    Error,
    ErrorCode,
    FutureError,
    HpxError,
    NetworkError,
    NotImplementedYet,
    ReservedConfigKey,
    UndeclaredConfigKey,
    throw_exception,
)
