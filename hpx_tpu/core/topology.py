"""Hardware topology — the hwloc-wrapper analog, TPU-first.

Reference analog: libs/core/topology (`hpx::threads::topology`: sockets/
cores/PUs, NUMA masks — SURVEY.md §2.1, §2.8's mapping table: "hwloc
topology (C)" → "jax.devices(), mesh axes, device.coords/ICI topology").

Host side reports what Python can see (cores); device side reports the
accelerator fleet: device kind, platform, per-device coords (the ICI
torus position on real TPU), memory stats, and process/slice layout for
multi-host runs.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["Topology", "get_topology"]


class Topology:
    """Singleton snapshot (hpx::threads::get_topology())."""

    # -- host ---------------------------------------------------------------
    def number_of_cores(self) -> int:
        return os.cpu_count() or 1

    def number_of_pus(self) -> int:
        # no hwloc: PUs == schedulable CPUs visible to this process
        try:
            return len(os.sched_getaffinity(0))
        except AttributeError:       # non-Linux
            return self.number_of_cores()

    # -- devices ------------------------------------------------------------
    def number_of_devices(self) -> int:
        import jax
        return len(jax.devices())

    def number_of_local_devices(self) -> int:
        import jax
        return len(jax.local_devices())

    def device_kind(self) -> str:
        import jax
        d = jax.devices()
        return d[0].device_kind if d else "none"

    def platform(self) -> str:
        import jax
        return jax.default_backend()

    def device_coords(self, index: int = 0) -> Optional[Tuple[int, ...]]:
        """ICI torus coordinates of a device (None on CPU/GPU meshes)."""
        import jax
        d = jax.devices()[index]
        return tuple(d.coords) if hasattr(d, "coords") else None

    def ici_shape(self) -> Optional[Tuple[int, ...]]:
        """Bounding box of the device coords = the physical torus shape
        (None when the platform exposes no coords)."""
        import jax
        coords = [d.coords for d in jax.devices() if hasattr(d, "coords")]
        if not coords:
            return None
        dims = len(coords[0])
        return tuple(max(c[i] for c in coords) + 1 for i in range(dims))

    def device_memory_stats(self, index: int = 0) -> Dict[str, int]:
        import jax
        try:
            return dict(jax.devices()[index].memory_stats() or {})
        except Exception:  # noqa: BLE001 — not all backends report
            return {}

    # -- processes (multi-host) ---------------------------------------------
    def number_of_processes(self) -> int:
        import jax
        return jax.process_count()

    def process_index(self) -> int:
        import jax
        return jax.process_index()

    def devices_by_process(self) -> Dict[int, List[Any]]:
        import jax
        out: Dict[int, List[Any]] = {}
        for d in jax.devices():
            out.setdefault(d.process_index, []).append(d)
        return out

    def __repr__(self) -> str:
        return (f"Topology(cores={self.number_of_cores()}, "
                f"devices={self.number_of_devices()} "
                f"[{self.device_kind()}@{self.platform()}])")


_topology: Optional[Topology] = None


def get_topology() -> Topology:
    global _topology
    if _topology is None:
        _topology = Topology()
    return _topology
