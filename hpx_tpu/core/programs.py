"""One get-or-build memo for compiled programs.

Reference analog: none needed — this is the TPU-side consequence of
XLA's trace-once model: any API that builds a traced closure per call
(decode entry points, sharded algorithm builders, FFT plans) must memo
the compiled program on the closure's BAKED constants or every call
retraces. One shared helper so cache policy (say, eviction or a debug
counter) has one home; each module keeps its own dict so keys never
collide across subsystems.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

# Installed by svc/progprof when the per-program profiler is active.
# None keeps the hot path identical to the unprofiled memo: cache hits
# never see the hook (the wrapped program is what got stored), and a
# miss pays one extra None-check.
_profile_hook: Optional[Callable[[Any, Callable[[], Any]], Any]] = None


def set_profile_hook(
        hook: Optional[Callable[[Any, Callable[[], Any]], Any]]) -> None:
    """Install (or clear, with None) the build-interposer the program
    profiler uses to time compiles and wrap programs for per-call
    accounting. The hook receives ``(key, build)`` and must return the
    value to cache — normally a callable proxy around ``build()``."""
    global _profile_hook
    _profile_hook = hook


def profile_hook() -> Optional[Callable[[Any, Callable[[], Any]], Any]]:
    return _profile_hook


def cached_program(cache: Dict[Any, Any], key: Any,
                   build: Callable[[], Any]) -> Any:
    prog = cache.get(key)
    if prog is None:
        hook = _profile_hook
        prog = cache[key] = build() if hook is None else hook(key, build)
    return prog
