"""One get-or-build memo for compiled programs.

Reference analog: none needed — this is the TPU-side consequence of
XLA's trace-once model: any API that builds a traced closure per call
(decode entry points, sharded algorithm builders, FFT plans) must memo
the compiled program on the closure's BAKED constants or every call
retraces. One shared helper so cache policy (say, eviction or a debug
counter) has one home; each module keeps its own dict so keys never
collide across subsystems.
"""

from __future__ import annotations

from typing import Any, Callable, Dict


def cached_program(cache: Dict[Any, Any], key: Any,
                   build: Callable[[], Any]) -> Any:
    prog = cache.get(key)
    if prog is None:
        prog = cache[key] = build()
    return prog
