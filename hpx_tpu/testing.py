"""Testing helpers.

Reference analog: libs/core/testing (HPX_TEST / HPX_TEST_EQ / HPX_TEST_LT
macros; hpx::util::report_errors returning the failure count as the process
exit code). Under pytest these map onto asserts, but the counter-based API is
kept so example programs can self-report like HPX example binaries do, and
perf tests can emit the JSON `perftests_report` shape.
"""

from __future__ import annotations

import json
import sys
import threading
import time
from typing import Any, Callable, Dict, List

_failures = 0
_lock = threading.Lock()


def _fail(msg: str) -> None:
    global _failures
    with _lock:
        _failures += 1
    sys.stderr.write(f"HPX_TEST failed: {msg}\n")


def HPX_TEST(cond: Any, msg: str = "") -> bool:
    if not cond:
        _fail(msg or "condition is false")
    return bool(cond)


def _all(cond: Any) -> bool:
    """Collapse a comparison result to bool; array-likes require all()."""
    try:
        return bool(cond)
    except Exception:
        import numpy as np
        return bool(np.all(np.asarray(cond)))


def HPX_TEST_EQ(a: Any, b: Any, msg: str = "") -> bool:
    ok = _all(a == b)
    if not ok:
        _fail(msg or f"{a!r} != {b!r}")
    return ok


def HPX_TEST_NEQ(a: Any, b: Any, msg: str = "") -> bool:
    ok = not _all(a == b)
    if not ok:
        _fail(msg or f"{a!r} == {b!r}")
    return ok


def HPX_TEST_LT(a: Any, b: Any, msg: str = "") -> bool:
    ok = _all(a < b)
    if not ok:
        _fail(msg or f"{a!r} !< {b!r}")
    return ok


def HPX_TEST_LTE(a: Any, b: Any, msg: str = "") -> bool:
    ok = _all(a <= b)
    if not ok:
        _fail(msg or f"{a!r} !<= {b!r}")
    return ok


def HPX_TEST_RANGE(lo: Any, x: Any, hi: Any, msg: str = "") -> bool:
    ok = _all(lo <= x) and _all(x <= hi)
    if not ok:
        _fail(msg or f"{x!r} not in [{lo!r}, {hi!r}]")
    return ok


def HPX_TEST_THROW(fn: Callable[[], Any], exc_type: type, msg: str = "") -> bool:
    try:
        fn()
    except exc_type:
        return True
    except Exception as e:  # noqa: BLE001
        _fail(msg or f"raised {type(e).__name__}, expected {exc_type.__name__}")
        return False
    _fail(msg or f"did not raise {exc_type.__name__}")
    return False


def report_errors() -> int:
    """Return accumulated failure count (HPX uses it as the exit code)."""
    with _lock:
        return _failures


def reset_errors() -> None:
    global _failures
    with _lock:
        _failures = 0


class PerftestsReport:
    """hpx::util::perftests_report analog: named timed runs -> JSON.

    Shape follows HPX's perftest JSON closely enough for the same tooling
    pattern (name, executor, series of samples, mean).
    """

    def __init__(self) -> None:
        self._results: List[Dict[str, Any]] = []

    def run(self, name: str, executor: str, fn: Callable[[], Any],
            steps: int = 5, warmup: int = 1) -> Dict[str, Any]:
        for _ in range(warmup):
            fn()
        samples = []
        for _ in range(steps):
            t0 = time.perf_counter()
            fn()
            samples.append(time.perf_counter() - t0)
        entry = {
            "name": name,
            "executor": executor,
            "series": samples,
            "mean": sum(samples) / len(samples),
            "min": min(samples),
        }
        self._results.append(entry)
        return entry

    def json(self) -> str:
        return json.dumps({"outputs": self._results})

    def print(self) -> None:
        print(self.json())
