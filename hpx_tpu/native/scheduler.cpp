// Native runtime core: lock-free work-stealing scheduler, monotonic
// timer, atomic counters.
//
// Reference analog: libs/core/schedulers (local_priority_queue_scheduler
// / abp work stealing) + libs/core/concurrency (lock-free structures) +
// libs/core/thread_pools (scheduling_loop) — re-designed for the
// TPU-native runtime where host tasks are orchestration (graph building,
// XLA dispatch, IO callbacks) rather than compute. Tasks enter as C
// function pointers; the Python binding (hpx_tpu/native/loader.py)
// provides a trampoline that re-enters the interpreter under the GIL.
//
// Scheduling discipline:
//   * per-worker LOCK-FREE Chase-Lev deques (Lê et al., "Correct and
//     Efficient Work-Stealing for Weak Memory Models", PPoPP'13):
//     owner pushes/takes LIFO at the bottom, thieves CAS-steal FIFO at
//     the top — no mutex anywhere on the worker hot path
//   * external (non-worker) submits go to small per-worker mutexed
//     inboxes — HPX's thread_queue stages "new tasks" the same way —
//     which workers drain into their own deque
//   * idle workers park on a condition variable with backoff; producers
//     only touch it when a racy read shows parked workers
//   * help_one() lets any thread (incl. a worker blocked on a future)
//     execute one queued task — the suspension/starvation-safety analog.

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <memory>
#include <mutex>
#include <thread>
#include <vector>

extern "C" {
typedef void (*hpxrt_task_fn)(void*);
}

namespace {

// ---------------------------------------------------------------------------
// Chase-Lev lock-free work-stealing deque of opaque pointers.
//
// Single owner thread calls push()/take(); any thread may call steal().
// The circular buffer grows by doubling; retired buffers are kept until
// destruction (a stealer may still be reading one — the standard simple
// reclamation policy; memory is bounded by 2x the high-water mark).
// ---------------------------------------------------------------------------

class CLDeque {
 public:
  explicit CLDeque(int64_t cap = 64) {
    array_.store(new Buf(cap), std::memory_order_relaxed);
  }

  ~CLDeque() {
    delete array_.load(std::memory_order_relaxed);
    for (Buf* b : retired_) delete b;
  }

  void push(void* x) {                       // owner only
    int64_t b = bottom_.load(std::memory_order_relaxed);
    int64_t t = top_.load(std::memory_order_acquire);
    Buf* a = array_.load(std::memory_order_relaxed);
    if (b - t > a->cap - 1) {
      a = grow(a, t, b);
    }
    a->put(b, x);
    std::atomic_thread_fence(std::memory_order_release);
    bottom_.store(b + 1, std::memory_order_relaxed);
  }

  void* take() {                             // owner only
    int64_t b = bottom_.load(std::memory_order_relaxed) - 1;
    Buf* a = array_.load(std::memory_order_relaxed);
    bottom_.store(b, std::memory_order_relaxed);
    std::atomic_thread_fence(std::memory_order_seq_cst);
    int64_t t = top_.load(std::memory_order_relaxed);
    void* x = nullptr;
    if (t <= b) {
      x = a->get(b);
      if (t == b) {
        // last element: race the thieves for it
        if (!top_.compare_exchange_strong(t, t + 1,
                                          std::memory_order_seq_cst,
                                          std::memory_order_relaxed))
          x = nullptr;
        bottom_.store(b + 1, std::memory_order_relaxed);
      }
    } else {
      bottom_.store(b + 1, std::memory_order_relaxed);
    }
    return x;
  }

  void* steal() {                            // any thread
    int64_t t = top_.load(std::memory_order_acquire);
    std::atomic_thread_fence(std::memory_order_seq_cst);
    int64_t b = bottom_.load(std::memory_order_acquire);
    if (t < b) {
      Buf* a = array_.load(std::memory_order_acquire);
      void* x = a->get(t);
      if (!top_.compare_exchange_strong(t, t + 1,
                                        std::memory_order_seq_cst,
                                        std::memory_order_relaxed))
        return nullptr;                      // lost the race: caller retries
      return x;
    }
    return nullptr;
  }

  int64_t size() const {
    int64_t b = bottom_.load(std::memory_order_relaxed);
    int64_t t = top_.load(std::memory_order_relaxed);
    return b > t ? b - t : 0;
  }

 private:
  struct Buf {
    const int64_t cap;                       // power of two
    std::unique_ptr<std::atomic<void*>[]> slots;
    explicit Buf(int64_t c)
        : cap(c), slots(new std::atomic<void*>[c]) {}
    void put(int64_t i, void* x) {
      slots[i & (cap - 1)].store(x, std::memory_order_relaxed);
    }
    void* get(int64_t i) {
      return slots[i & (cap - 1)].load(std::memory_order_relaxed);
    }
  };

  Buf* grow(Buf* a, int64_t t, int64_t b) {
    Buf* na = new Buf(a->cap * 2);
    for (int64_t i = t; i < b; ++i) na->put(i, a->get(i));
    retired_.push_back(a);                   // owner-only: no lock needed
    array_.store(na, std::memory_order_release);
    return na;
  }

  std::atomic<int64_t> top_{0};
  std::atomic<int64_t> bottom_{0};
  std::atomic<Buf*> array_{nullptr};
  std::vector<Buf*> retired_;               // owner-managed
};

// ---------------------------------------------------------------------------
// pool
// ---------------------------------------------------------------------------

struct Task {
  hpxrt_task_fn fn;
  void* arg;
};

struct Inbox {                               // external-submit staging
  std::mutex m;
  std::deque<Task*> q;
};

struct Pool;
thread_local Pool* tls_pool = nullptr;
thread_local int tls_wid = -1;

struct Pool {
  std::vector<std::unique_ptr<CLDeque>> deques;
  std::vector<std::unique_ptr<Inbox>> inboxes;
  std::vector<std::thread> workers;
  std::mutex cv_m;
  std::condition_variable cv;
  std::atomic<int> idle{0};
  std::atomic<bool> shutdown{false};
  std::atomic<uint64_t> executed{0};
  std::atomic<uint64_t> stolen{0};
  std::atomic<long> pending{0};
  std::atomic<unsigned> rr{0};

  explicit Pool(int nthreads) {
    deques.reserve(nthreads);
    inboxes.reserve(nthreads);
    for (int i = 0; i < nthreads; ++i) {
      deques.emplace_back(std::make_unique<CLDeque>());
      inboxes.emplace_back(std::make_unique<Inbox>());
    }
    workers.reserve(nthreads);
    for (int i = 0; i < nthreads; ++i)
      workers.emplace_back([this, i] { worker(i); });
  }

  ~Pool() {
    // drain leftovers (tasks submitted after/during shutdown)
    for (auto& d : deques)
      while (void* x = d->take()) delete static_cast<Task*>(x);
    for (auto& ib : inboxes)
      for (Task* t : ib->q) delete t;
  }

  Task* drain_inbox(int wid) {
    Inbox& ib = *inboxes[wid];
    std::lock_guard<std::mutex> lk(ib.m);
    if (ib.q.empty()) return nullptr;
    Task* t = ib.q.front();
    ib.q.pop_front();
    // move the rest into the owner's lock-free deque so subsequent
    // pops skip the mutex entirely
    CLDeque& d = *deques[wid];
    while (!ib.q.empty()) {
      d.push(ib.q.front());
      ib.q.pop_front();
    }
    return t;
  }

  Task* try_pop(int wid, bool owner) {
    const int n = static_cast<int>(deques.size());
    if (owner) {
      if (void* x = deques[wid]->take()) return static_cast<Task*>(x);
      if (Task* t = drain_inbox(wid)) return t;
    }
    for (int off = owner ? 1 : 0; off < n; ++off) {
      int vid = (wid + off) % n;
      if (void* x = deques[vid]->steal()) {
        stolen.fetch_add(1, std::memory_order_relaxed);
        return static_cast<Task*>(x);
      }
      Inbox& ib = *inboxes[vid];
      std::unique_lock<std::mutex> lk(ib.m, std::try_to_lock);
      if (lk.owns_lock() && !ib.q.empty()) {
        Task* t = ib.q.front();
        ib.q.pop_front();
        if (off != 0) stolen.fetch_add(1, std::memory_order_relaxed);
        return t;
      }
    }
    return nullptr;
  }

  void run_task(Task* t) {
    pending.fetch_sub(1, std::memory_order_relaxed);
    t->fn(t->arg);  // exceptions cannot cross the C boundary; the Python
                    // trampoline captures them into futures
    delete t;
    executed.fetch_add(1, std::memory_order_relaxed);
  }

  void worker(int wid) {
    tls_pool = this;
    tls_wid = wid;
    int misses = 0;
    for (;;) {
      if (Task* t = try_pop(wid, /*owner=*/true)) {
        run_task(t);
        misses = 0;
        continue;
      }
      if (shutdown.load(std::memory_order_acquire) &&
          pending.load(std::memory_order_acquire) <= 0)
        return;
      if (++misses < 4) {
        // shallow park: cheap latency for bursty gaps; a submit that
        // lands here (idle not yet raised) is picked up within ~ms
        std::unique_lock<std::mutex> lk(cv_m);
        idle.fetch_add(1, std::memory_order_seq_cst);
        cv.wait_for(lk, std::chrono::milliseconds(1 << misses));
        idle.fetch_sub(1, std::memory_order_relaxed);
      } else {
        // deep park: INDEFINITE wait, zero idle churn. No lost wakeup:
        // submit orders pending++ BEFORE its idle check, and we raise
        // idle (seq_cst) before testing the predicate under the lock —
        // either submit sees idle>0 and notifies under this mutex, or
        // the predicate sees pending>0 and skips the wait.
        std::unique_lock<std::mutex> lk(cv_m);
        idle.fetch_add(1, std::memory_order_seq_cst);
        cv.wait(lk, [this] {
          return pending.load(std::memory_order_acquire) > 0 ||
                 shutdown.load(std::memory_order_acquire);
        });
        idle.fetch_sub(1, std::memory_order_relaxed);
        misses = 0;
      }
    }
  }

  // Batch submit: ONE pending update, one lock per inbox touched, one
  // wake — the per-task interpreter cost of crossing the C ABI n times
  // (the future_overhead gap vs the reference's C++ scheduler) collapses
  // into a single call. Task args are the contiguous ids
  // [start, start+count): the Python side registers its callables under
  // those ids before calling.
  void submit_many(hpxrt_task_fn fn, size_t start, int count) {
    if (count <= 0) return;
    pending.fetch_add(count, std::memory_order_seq_cst);
    if (tls_pool == this && tls_wid >= 0) {
      CLDeque& d = *deques[tls_wid];               // owner: lock-free
      for (int i = 0; i < count; ++i)
        d.push(new Task{fn, reinterpret_cast<void*>(start + i)});
    } else {
      const int nw = static_cast<int>(inboxes.size());
      const unsigned base = rr.fetch_add(1, std::memory_order_relaxed);
      int i = 0;
      for (int w = 0; w < nw && i < count; ++w) {
        const int hi = static_cast<int>(
            (static_cast<int64_t>(count) * (w + 1)) / nw);
        if (hi <= i) continue;                     // empty slice
        Inbox& ib = *inboxes[(base + w) % nw];
        std::lock_guard<std::mutex> lk(ib.m);
        for (; i < hi; ++i)
          ib.q.push_back(new Task{fn, reinterpret_cast<void*>(start + i)});
      }
    }
    if (idle.load(std::memory_order_seq_cst) > 0) {
      std::lock_guard<std::mutex> lk(cv_m);
      cv.notify_all();
    }
  }

  void submit(hpxrt_task_fn fn, void* arg) {
    Task* t = new Task{fn, arg};
    // seq_cst: must be globally ordered BEFORE the idle check below
    // (pairs with the deep-park handshake in worker())
    pending.fetch_add(1, std::memory_order_seq_cst);
    if (tls_pool == this && tls_wid >= 0) {
      deques[tls_wid]->push(t);              // owner fast path: lock-free
    } else {
      int wid = static_cast<int>(
          rr.fetch_add(1, std::memory_order_relaxed) % inboxes.size());
      Inbox& ib = *inboxes[wid];
      std::lock_guard<std::mutex> lk(ib.m);
      ib.q.push_back(t);
    }
    if (idle.load(std::memory_order_seq_cst) > 0) {
      std::lock_guard<std::mutex> lk(cv_m);
      cv.notify_one();
    }
  }

  int help_one() {
    bool owner = (tls_pool == this && tls_wid >= 0);
    int wid = owner ? tls_wid : 0;
    Task* t = try_pop(wid, owner);
    if (!t) return 0;
    run_task(t);
    return 1;
  }

  void stop() {
    shutdown.store(true, std::memory_order_release);
    {
      std::lock_guard<std::mutex> lk(cv_m);
      cv.notify_all();
    }
    for (auto& w : workers)
      if (w.joinable() && w.get_id() != std::this_thread::get_id()) w.join();
  }
};

}  // namespace

extern "C" {

void* hpxrt_pool_create(int nthreads) {
  if (nthreads < 1) nthreads = 1;
  return new Pool(nthreads);
}

void hpxrt_pool_submit(void* pool, hpxrt_task_fn fn, void* arg) {
  static_cast<Pool*>(pool)->submit(fn, arg);
}

void hpxrt_pool_submit_many(void* pool, hpxrt_task_fn fn, size_t start,
                            int count) {
  static_cast<Pool*>(pool)->submit_many(fn, start, count);
}

int hpxrt_pool_help_one(void* pool) {
  return static_cast<Pool*>(pool)->help_one();
}

int hpxrt_pool_in_worker(void* pool) {
  return tls_pool == static_cast<Pool*>(pool) && tls_wid >= 0;
}

void hpxrt_pool_shutdown(void* pool) {
  Pool* p = static_cast<Pool*>(pool);
  p->stop();
  delete p;
}

uint64_t hpxrt_pool_executed(void* pool) {
  return static_cast<Pool*>(pool)->executed.load(std::memory_order_relaxed);
}

uint64_t hpxrt_pool_stolen(void* pool) {
  return static_cast<Pool*>(pool)->stolen.load(std::memory_order_relaxed);
}

long hpxrt_pool_pending(void* pool) {
  long v = static_cast<Pool*>(pool)->pending.load(std::memory_order_relaxed);
  return v > 0 ? v : 0;
}

int hpxrt_pool_idle(void* pool) {
  // workers currently parked on the cv (shallow or deep) — the
  // instantaneous idle count behind the idle-rate counter
  return static_cast<Pool*>(pool)->idle.load(std::memory_order_relaxed);
}

// Per-worker queue depth (deque + staged inbox) — the counter feed for
// /threads{.../pool#<name>/worker-thread#i}/queue/length. Racy reads by
// design (relaxed size() + try-lock on the inbox): a perf counter must
// never contend with the scheduler hot path.
long hpxrt_pool_queue_len(void* pool, int wid) {
  Pool* p = static_cast<Pool*>(pool);
  if (wid < 0 || wid >= static_cast<int>(p->deques.size())) return -1;
  long n = static_cast<long>(p->deques[wid]->size());
  Inbox& ib = *p->inboxes[wid];
  std::unique_lock<std::mutex> lk(ib.m, std::try_to_lock);
  if (lk.owns_lock()) n += static_cast<long>(ib.q.size());
  return n;
}

// -- standalone Chase-Lev deque (lock-free structure surface) ---------------
// Exposed for direct use and stress testing: items are opaque pointers;
// push/take are OWNER-thread ops, steal is any-thread (ctypes releases
// the GIL, so Python threads genuinely race these).

void* hpxrt_cldeque_create() { return new CLDeque(); }

void hpxrt_cldeque_push(void* d, void* item) {
  static_cast<CLDeque*>(d)->push(item);
}

void* hpxrt_cldeque_take(void* d) { return static_cast<CLDeque*>(d)->take(); }

void* hpxrt_cldeque_steal(void* d) {
  return static_cast<CLDeque*>(d)->steal();
}

long hpxrt_cldeque_size(void* d) {
  return static_cast<long>(static_cast<CLDeque*>(d)->size());
}

void hpxrt_cldeque_destroy(void* d) { delete static_cast<CLDeque*>(d); }

// -- high-resolution timer (hpx::chrono::high_resolution_timer analog) -----

uint64_t hpxrt_now_ns() {
  return static_cast<uint64_t>(
      std::chrono::duration_cast<std::chrono::nanoseconds>(
          std::chrono::steady_clock::now().time_since_epoch())
          .count());
}

// -- atomic counters (performance_counters raw-counter substrate) ----------

void* hpxrt_counter_new() { return new std::atomic<int64_t>(0); }

void hpxrt_counter_add(void* c, int64_t v) {
  static_cast<std::atomic<int64_t>*>(c)->fetch_add(v,
                                                   std::memory_order_relaxed);
}

int64_t hpxrt_counter_get(void* c) {
  return static_cast<std::atomic<int64_t>*>(c)->load(
      std::memory_order_relaxed);
}

void hpxrt_counter_free(void* c) {
  delete static_cast<std::atomic<int64_t>*>(c);
}

}  // extern "C"
