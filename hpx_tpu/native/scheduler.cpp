// Native runtime core: work-stealing task scheduler, monotonic timer,
// atomic counters.
//
// Reference analog: libs/core/schedulers (local_priority_queue_scheduler /
// abp work stealing) + libs/core/thread_pools (scheduled_thread_pool,
// scheduling_loop) — re-designed for the TPU-native runtime where host
// tasks are orchestration (graph building, XLA dispatch, IO callbacks)
// rather than compute. Tasks enter as C function pointers; the Python
// binding (hpx_tpu/native/loader.py) provides a trampoline that re-enters
// the interpreter under the GIL.
//
// Scheduling discipline (same as the Python fallback pool, so the two are
// interchangeable behind one interface):
//   * per-worker deques; owner pops LIFO (hot cache), thieves steal FIFO
//   * external submits round-robin across queues
//   * idle workers park on a condition variable
//   * help_one() lets any thread (incl. a worker blocked on a future)
//     execute one queued task — the suspension/starvation-safety analog.

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <memory>
#include <mutex>
#include <thread>
#include <vector>

extern "C" {
typedef void (*hpxrt_task_fn)(void*);
}

namespace {

struct Task {
  hpxrt_task_fn fn;
  void* arg;
};

struct Queue {
  std::mutex m;
  std::deque<Task> q;
};

struct Pool;
thread_local Pool* tls_pool = nullptr;
thread_local int tls_wid = -1;

struct Pool {
  std::vector<std::unique_ptr<Queue>> queues;
  std::vector<std::thread> workers;
  std::mutex cv_m;
  std::condition_variable cv;
  long pending = 0;  // guarded by cv_m
  bool shutdown = false;
  std::atomic<uint64_t> executed{0};
  std::atomic<uint64_t> stolen{0};
  std::atomic<unsigned> rr{0};

  explicit Pool(int nthreads) {
    queues.reserve(nthreads);
    for (int i = 0; i < nthreads; ++i)
      queues.emplace_back(std::make_unique<Queue>());
    workers.reserve(nthreads);
    for (int i = 0; i < nthreads; ++i)
      workers.emplace_back([this, i] { worker(i); });
  }

  bool try_pop(int wid, Task* out) {
    {
      Queue& mine = *queues[wid];
      std::lock_guard<std::mutex> lk(mine.m);
      if (!mine.q.empty()) {
        *out = mine.q.back();  // own queue: LIFO
        mine.q.pop_back();
        return true;
      }
    }
    const int n = static_cast<int>(queues.size());
    for (int off = 1; off < n; ++off) {
      Queue& victim = *queues[(wid + off) % n];
      std::lock_guard<std::mutex> lk(victim.m);
      if (!victim.q.empty()) {
        *out = victim.q.front();  // steal: FIFO
        victim.q.pop_front();
        stolen.fetch_add(1, std::memory_order_relaxed);
        return true;
      }
    }
    return false;
  }

  void run_task(const Task& t) {
    {
      std::lock_guard<std::mutex> lk(cv_m);
      --pending;
    }
    t.fn(t.arg);  // exceptions cannot cross the C boundary; the Python
                  // trampoline captures them into futures
    executed.fetch_add(1, std::memory_order_relaxed);
  }

  void worker(int wid) {
    tls_pool = this;
    tls_wid = wid;
    for (;;) {
      Task t;
      if (try_pop(wid, &t)) {
        run_task(t);
        continue;
      }
      std::unique_lock<std::mutex> lk(cv_m);
      cv.wait(lk, [this] { return pending > 0 || shutdown; });
      if (shutdown && pending == 0) return;
    }
  }

  void submit(hpxrt_task_fn fn, void* arg) {
    int wid = (tls_pool == this && tls_wid >= 0)
                  ? tls_wid
                  : static_cast<int>(rr.fetch_add(1, std::memory_order_relaxed) %
                                     queues.size());
    {
      Queue& q = *queues[wid];
      std::lock_guard<std::mutex> lk(q.m);
      q.q.push_back(Task{fn, arg});
    }
    {
      std::lock_guard<std::mutex> lk(cv_m);
      ++pending;
    }
    cv.notify_one();
  }

  int help_one() {
    int wid = (tls_pool == this && tls_wid >= 0) ? tls_wid : 0;
    Task t;
    if (!try_pop(wid, &t)) return 0;
    run_task(t);
    return 1;
  }

  void stop() {
    {
      std::lock_guard<std::mutex> lk(cv_m);
      shutdown = true;
    }
    cv.notify_all();
    for (auto& w : workers)
      if (w.joinable() && w.get_id() != std::this_thread::get_id()) w.join();
  }
};

}  // namespace

extern "C" {

void* hpxrt_pool_create(int nthreads) {
  if (nthreads < 1) nthreads = 1;
  return new Pool(nthreads);
}

void hpxrt_pool_submit(void* pool, hpxrt_task_fn fn, void* arg) {
  static_cast<Pool*>(pool)->submit(fn, arg);
}

int hpxrt_pool_help_one(void* pool) {
  return static_cast<Pool*>(pool)->help_one();
}

int hpxrt_pool_in_worker(void* pool) {
  return tls_pool == static_cast<Pool*>(pool) && tls_wid >= 0;
}

void hpxrt_pool_shutdown(void* pool) {
  Pool* p = static_cast<Pool*>(pool);
  p->stop();
  delete p;
}

uint64_t hpxrt_pool_executed(void* pool) {
  return static_cast<Pool*>(pool)->executed.load(std::memory_order_relaxed);
}

uint64_t hpxrt_pool_stolen(void* pool) {
  return static_cast<Pool*>(pool)->stolen.load(std::memory_order_relaxed);
}

long hpxrt_pool_pending(void* pool) {
  Pool* p = static_cast<Pool*>(pool);
  std::lock_guard<std::mutex> lk(p->cv_m);
  return p->pending;
}

// -- high-resolution timer (hpx::chrono::high_resolution_timer analog) -----

uint64_t hpxrt_now_ns() {
  return static_cast<uint64_t>(
      std::chrono::duration_cast<std::chrono::nanoseconds>(
          std::chrono::steady_clock::now().time_since_epoch())
          .count());
}

// -- atomic counters (performance_counters raw-counter substrate) ----------

void* hpxrt_counter_new() { return new std::atomic<int64_t>(0); }

void hpxrt_counter_add(void* c, int64_t v) {
  static_cast<std::atomic<int64_t>*>(c)->fetch_add(v,
                                                   std::memory_order_relaxed);
}

int64_t hpxrt_counter_get(void* c) {
  return static_cast<std::atomic<int64_t>*>(c)->load(
      std::memory_order_relaxed);
}

void hpxrt_counter_free(void* c) {
  delete static_cast<std::atomic<int64_t>*>(c);
}

}  // extern "C"
