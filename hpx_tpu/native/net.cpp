// Native TCP parcel transport.
//
// Reference analog: the parcelport layer (libs/full/parcelset +
// plugins/parcelport/tcp; the fork's libfabric parcelport is the RDMA
// sibling) — re-designed for the TPU runtime's control plane: bulk data
// rides ICI via XLA collectives, so this transport carries parcels
// (serialized actions, AGAS traffic, host-side collective rendezvous),
// which are small and latency-sensitive. Design:
//   * one epoll IO thread per endpoint: accepts, reads 4-byte-LE length
//     prefixed frames, invokes a callback (the Python binding re-enters
//     the interpreter under the GIL and enqueues the parcel)
//   * sends happen on the caller's thread over a per-peer mutex —
//     blocking socket writes; fine for control-plane message sizes
//   * peers are small integer ids assigned by hpxrt_net_connect /
//     accept order; the handshake protocol above this (loader.py) maps
//     them to locality ids.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <thread>
#include <vector>

extern "C" {
// cb(user, peer_id, data, len): data valid only during the call
typedef void (*hpxrt_net_cb)(void* user, int peer_id, const uint8_t* data,
                             uint64_t len);
}

namespace {

struct Peer {
  int fd = -1;           // guarded by send_mu for close-vs-send races
  std::mutex send_mu;
  // receive reassembly (IO thread only)
  std::vector<uint8_t> buf;
};

struct Net {
  int listen_fd = -1;
  int epoll_fd = -1;
  int wake_fd = -1;
  uint16_t port = 0;
  std::thread io;
  std::atomic<bool> stop{false};
  hpxrt_net_cb cb = nullptr;
  void* cb_user = nullptr;

  std::mutex peers_mu;
  std::map<int, std::shared_ptr<Peer>> peers;
  int next_peer = 0;

  int add_peer(int fd) {
    auto p = std::make_shared<Peer>();
    p->fd = fd;
    int id;
    {
      std::lock_guard<std::mutex> lk(peers_mu);
      id = next_peer++;
      peers[id] = p;
    }
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.fd = fd;
    // map fd->peer id via events on fd; store id in u64 alongside
    ev.data.u64 = (static_cast<uint64_t>(id) << 32) | static_cast<uint32_t>(fd);
    epoll_ctl(epoll_fd, EPOLL_CTL_ADD, fd, &ev);
    return id;
  }

  std::shared_ptr<Peer> get_peer(int id) {
    std::lock_guard<std::mutex> lk(peers_mu);
    auto it = peers.find(id);
    return it == peers.end() ? nullptr : it->second;
  }

  void drop_peer_by_fd(int fd) {
    std::shared_ptr<Peer> victim;
    {
      std::lock_guard<std::mutex> lk(peers_mu);
      for (auto it = peers.begin(); it != peers.end(); ++it) {
        if (it->second->fd == fd) {
          victim = it->second;
          peers.erase(it);
          break;
        }
      }
    }
    if (victim) {
      // a sender may be mid-writev: take its send mutex before closing,
      // and mark fd invalid so later sends fail cleanly instead of
      // writing into a recycled fd number
      std::lock_guard<std::mutex> lk(victim->send_mu);
      close(victim->fd);
      victim->fd = -1;
    }
  }

  void io_loop() {
    std::vector<epoll_event> events(64);
    std::vector<uint8_t> rdbuf(1 << 16);
    while (!stop.load(std::memory_order_relaxed)) {
      int n = epoll_wait(epoll_fd, events.data(),
                         static_cast<int>(events.size()), 200);
      for (int i = 0; i < n; ++i) {
        int fd = static_cast<uint32_t>(events[i].data.u64 & 0xffffffffu);
        int pid = static_cast<int>(events[i].data.u64 >> 32);
        if (fd == wake_fd) {
          uint64_t tmp;
          (void)!read(wake_fd, &tmp, sizeof(tmp));
          continue;
        }
        if (fd == listen_fd) {
          for (;;) {
            int cfd = accept(listen_fd, nullptr, nullptr);
            if (cfd < 0) break;
            int one = 1;
            setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
            add_peer(cfd);
          }
          continue;
        }
        // data on a peer socket
        ssize_t r = read(fd, rdbuf.data(), rdbuf.size());
        if (r <= 0) {
          if (r == 0 || (errno != EAGAIN && errno != EINTR)) {
            epoll_ctl(epoll_fd, EPOLL_CTL_DEL, fd, nullptr);
            drop_peer_by_fd(fd);
          }
          continue;
        }
        auto p = get_peer(pid);
        if (!p) continue;
        p->buf.insert(p->buf.end(), rdbuf.data(), rdbuf.data() + r);
        // extract complete frames
        size_t off = 0;
        while (p->buf.size() - off >= 4) {
          uint32_t len;
          std::memcpy(&len, p->buf.data() + off, 4);
          if (p->buf.size() - off - 4 < len) break;
          if (cb) cb(cb_user, pid, p->buf.data() + off + 4, len);
          off += 4 + len;
        }
        if (off) p->buf.erase(p->buf.begin(), p->buf.begin() + off);
      }
    }
  }
};

int make_listener(uint16_t* port, const char* bind_ip) {
  int fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  // bind a SPECIFIC interface (the security default — 0.0.0.0 only when
  // the caller passes it explicitly)
  if (inet_pton(AF_INET, bind_ip, &addr.sin_addr) != 1) {
    close(fd);
    return -1;
  }
  addr.sin_port = htons(*port);
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      listen(fd, 64) < 0) {
    close(fd);
    return -1;
  }
  socklen_t alen = sizeof(addr);
  getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  *port = ntohs(addr.sin_port);
  return fd;
}

}  // namespace

extern "C" {

// Create endpoint listening on port (0 = ephemeral) bound to the IPv4
// literal bind_ip. Returns handle or null.
void* hpxrt_net_create3(uint16_t port, const char* bind_ip) {
  auto* net = new Net();
  net->port = port;
  net->listen_fd = make_listener(&net->port, bind_ip);
  if (net->listen_fd < 0) {
    delete net;
    return nullptr;
  }
  net->epoll_fd = epoll_create1(0);
  net->wake_fd = eventfd(0, EFD_NONBLOCK);
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.u64 = (0ull << 32) | static_cast<uint32_t>(net->listen_fd);
  epoll_ctl(net->epoll_fd, EPOLL_CTL_ADD, net->listen_fd, &ev);
  epoll_event wev{};
  wev.events = EPOLLIN;
  wev.data.u64 = (0ull << 32) | static_cast<uint32_t>(net->wake_fd);
  epoll_ctl(net->epoll_fd, EPOLL_CTL_ADD, net->wake_fd, &wev);
  return net;
}

void* hpxrt_net_create2(uint16_t port, int bind_any) {
  return hpxrt_net_create3(port, bind_any ? "0.0.0.0" : "127.0.0.1");
}

void* hpxrt_net_create(uint16_t port) { return hpxrt_net_create2(port, 0); }

uint16_t hpxrt_net_port(void* h) { return static_cast<Net*>(h)->port; }

void hpxrt_net_set_callback(void* h, hpxrt_net_cb cb, void* user) {
  auto* net = static_cast<Net*>(h);
  net->cb = cb;
  net->cb_user = user;
}

void hpxrt_net_start(void* h) {
  auto* net = static_cast<Net*>(h);
  net->io = std::thread([net] { net->io_loop(); });
}

// Connect to host:port; returns peer id (>=0) or -1.
int hpxrt_net_connect(void* h, const char* host, uint16_t port) {
  auto* net = static_cast<Net*>(h);
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    close(fd);
    return -1;
  }
  if (connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
    close(fd);
    return -1;
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return net->add_peer(fd);
}

// Blocking framed send on the caller's thread. Returns 0 on success.
int hpxrt_net_send(void* h, int peer_id, const uint8_t* data, uint64_t len) {
  auto* net = static_cast<Net*>(h);
  if (len > 0xffffffffull) return -1;  // u32 frame-length limit
  auto p = net->get_peer(peer_id);
  if (!p) return -1;
  std::lock_guard<std::mutex> lk(p->send_mu);
  if (p->fd < 0) return -1;            // peer dropped while we waited
  uint32_t hdr = static_cast<uint32_t>(len);
  struct iovec iov[2];
  iov[0].iov_base = &hdr;
  iov[0].iov_len = 4;
  iov[1].iov_base = const_cast<uint8_t*>(data);
  iov[1].iov_len = len;
  size_t total = 4 + len;
  size_t sent = 0;
  while (sent < total) {
    ssize_t w = writev(p->fd, iov, 2);
    if (w < 0) {
      if (errno == EINTR) continue;
      return -1;
    }
    sent += static_cast<size_t>(w);
    // adjust iov for partial writes
    size_t skip = static_cast<size_t>(w);
    for (auto& v : iov) {
      size_t s = std::min(skip, v.iov_len);
      v.iov_base = static_cast<uint8_t*>(v.iov_base) + s;
      v.iov_len -= s;
      skip -= s;
    }
  }
  return 0;
}

void hpxrt_net_destroy(void* h) {
  auto* net = static_cast<Net*>(h);
  net->stop.store(true);
  uint64_t one = 1;
  (void)!write(net->wake_fd, &one, sizeof(one));
  if (net->io.joinable()) net->io.join();
  {
    std::lock_guard<std::mutex> lk(net->peers_mu);
    for (auto& kv : net->peers) {
      std::lock_guard<std::mutex> slk(kv.second->send_mu);
      close(kv.second->fd);
      kv.second->fd = -1;
    }
    net->peers.clear();
  }
  close(net->listen_fd);
  close(net->epoll_fd);
  close(net->wake_fd);
  delete net;
}

}  // extern "C"
