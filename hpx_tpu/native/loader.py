"""ctypes binding for the native runtime core (libhpx_tpu_rt.so).

Builds the shared library on first use if g++ is available (no pybind11 in
this environment — plain C ABI + ctypes, per the project's binding policy).
Falls back cleanly: callers must handle native_lib() returning None and use
the pure-Python implementations.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Any, Callable, Dict, Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "libhpx_tpu_rt.so")

_lib: Optional[ctypes.CDLL] = None
_lib_tried = False
_lib_lock = threading.Lock()

# live NativePool instances, for the perf-counter registry (weak: a
# pool's lifetime is owned by its creator, not by observability).
# WeakSet is NOT thread-safe — all access under _pools_lock (counter
# threads snapshot while constructors add).
import weakref

_live_pools: "weakref.WeakSet" = weakref.WeakSet()
_pools_lock = threading.Lock()


def live_native_pools():
    """Snapshot of live NativePool instances (perf-counter discovery)."""
    with _pools_lock:
        pools = list(_live_pools)
    return [p for p in pools if not p._shut]


def _find_pool(name: str):
    with _pools_lock:
        pools = list(_live_pools)
    for p in pools:
        if p.name == name and not p._shut:
            return p
    return None


def native_pool_stat(name: str, key: str) -> float:
    """Counter feed, resolved by pool NAME at call time: a recreated
    same-name pool is picked up automatically, and a dead pool reads 0
    (no stale-instance weakrefs)."""
    p = _find_pool(name)
    if p is None:
        return 0.0
    return float(p.stats().get(key, 0))


def native_pool_queue_len(name: str, wid: int) -> int:
    """Per-worker queue depth by pool name (0 when absent/shut/out of
    range — a recreated pool may have fewer workers)."""
    p = _find_pool(name)
    return 0 if p is None else p.queue_length(wid)

_TASK_FN = ctypes.CFUNCTYPE(None, ctypes.c_size_t)


def _build() -> bool:
    try:
        subprocess.run(["make", "-C", _HERE], check=True,
                       capture_output=True, timeout=120)
        return os.path.exists(_SO)
    except Exception:
        return False


def native_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _lib_tried
    if _lib is not None or _lib_tried:
        return _lib
    with _lib_lock:
        if _lib is not None or _lib_tried:
            return _lib
        _lib_tried = True
        # Always invoke make (it is incremental): a stale prebuilt .so —
        # the .so is gitignored, sources are not — would otherwise be
        # loaded and fail symbol binding after a source update.
        if not _build() and not os.path.exists(_SO):
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        lib.hpxrt_pool_create.restype = ctypes.c_void_p
        lib.hpxrt_pool_create.argtypes = [ctypes.c_int]
        lib.hpxrt_pool_submit.argtypes = [ctypes.c_void_p, _TASK_FN,
                                          ctypes.c_size_t]
        if hasattr(lib, "hpxrt_pool_submit_many"):
            # probe, not hard bind: a stale prebuilt .so (copied between
            # checkouts) lacks the symbol; NativePool then falls back to
            # per-task submits
            lib.hpxrt_pool_submit_many.argtypes = [
                ctypes.c_void_p, _TASK_FN, ctypes.c_size_t, ctypes.c_int]
        lib.hpxrt_pool_help_one.restype = ctypes.c_int
        lib.hpxrt_pool_help_one.argtypes = [ctypes.c_void_p]
        lib.hpxrt_pool_in_worker.restype = ctypes.c_int
        lib.hpxrt_pool_in_worker.argtypes = [ctypes.c_void_p]
        lib.hpxrt_pool_shutdown.argtypes = [ctypes.c_void_p]
        lib.hpxrt_pool_executed.restype = ctypes.c_uint64
        lib.hpxrt_pool_executed.argtypes = [ctypes.c_void_p]
        lib.hpxrt_pool_stolen.restype = ctypes.c_uint64
        lib.hpxrt_pool_stolen.argtypes = [ctypes.c_void_p]
        lib.hpxrt_pool_pending.restype = ctypes.c_long
        lib.hpxrt_pool_pending.argtypes = [ctypes.c_void_p]
        if hasattr(lib, "hpxrt_pool_queue_len"):   # stale-.so tolerant
            lib.hpxrt_pool_queue_len.restype = ctypes.c_long
            lib.hpxrt_pool_queue_len.argtypes = [ctypes.c_void_p,
                                                 ctypes.c_int]
        if hasattr(lib, "hpxrt_pool_idle"):
            lib.hpxrt_pool_idle.restype = ctypes.c_int
            lib.hpxrt_pool_idle.argtypes = [ctypes.c_void_p]
        lib.hpxrt_now_ns.restype = ctypes.c_uint64
        lib.hpxrt_counter_new.restype = ctypes.c_void_p
        lib.hpxrt_counter_add.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.hpxrt_counter_get.restype = ctypes.c_int64
        lib.hpxrt_counter_get.argtypes = [ctypes.c_void_p]
        lib.hpxrt_counter_free.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def now_ns() -> int:
    lib = native_lib()
    if lib is not None:
        return lib.hpxrt_now_ns()
    import time
    return time.monotonic_ns()


class NativePool:
    """Work-stealing pool backed by C++ threads.

    Python tasks are kept in an id-keyed registry; a single CFUNCTYPE
    trampoline (which re-acquires the GIL) dispatches by id. Conforms to
    the same interface as runtime.threadpool.WorkStealingPool so futures'
    work-helping treats both uniformly.
    """

    def __init__(self, num_threads: int, name: str = "native") -> None:
        lib = native_lib()
        if lib is None:
            raise RuntimeError("native runtime library unavailable")
        self._lib = lib
        self.name = name
        self._n = max(1, num_threads)
        self._handle = lib.hpxrt_pool_create(self._n)
        self._tasks: Dict[int, tuple] = {}
        self._tasks_lock = threading.Lock()
        self._next_id = 0
        self._shut = False
        self._shutdown_lock = threading.Lock()
        self._last_stats = {"executed": 0, "stolen": 0, "pending": 0,
                            "threads": self._n}

        # The trampoline must outlive every submitted task — bind it to the
        # instance so ctypes keeps the closure alive.
        def _tramp(arg: int) -> None:
            from ..runtime.threadpool import _worker_of
            if getattr(_worker_of, "pool", None) is None and \
                    self._lib.hpxrt_pool_in_worker(self._handle):
                _worker_of.pool = self  # register for future work-helping
            with self._tasks_lock:
                task = self._tasks.pop(arg, None)
            if task is None:
                return
            fn, args, kwargs = task
            from ..runtime import threadpool as _tp
            obs = _tp._task_observer
            if obs is not None:
                import time as _time
                try:  # observers must never break tasks or kill workers
                    obs("start", fn, None, args)
                except BaseException:  # noqa: BLE001
                    pass
                t0 = _time.monotonic()
            try:
                fn(*args, **kwargs)
            except BaseException:  # noqa: BLE001 — mirror Python pool
                import traceback
                traceback.print_exc()
            if obs is not None:
                try:
                    obs("stop", fn, _time.monotonic() - t0, args)
                except BaseException:  # noqa: BLE001
                    pass

        self._tramp = _TASK_FN(_tramp)
        with _pools_lock:
            _live_pools.add(self)

    @property
    def num_threads(self) -> int:
        return self._n

    def queue_length(self, wid: int) -> int:
        """ONE worker's queue depth (lock-free deque + staged inbox);
        0 after shutdown or out of range. Counter feed only — the C
        read is racy by design, and the shutdown lock pins the handle
        against the free in shutdown() (counters poll from arbitrary
        threads)."""
        with self._shutdown_lock:
            if self._shut or \
                    not hasattr(self._lib, "hpxrt_pool_queue_len"):
                return 0
            return max(0, int(self._lib.hpxrt_pool_queue_len(
                self._handle, wid)))

    def queue_lengths(self) -> list:
        return [self.queue_length(i) for i in range(self._n)]

    def submit(self, fn: Callable[..., Any], *args: Any, **kwargs: Any) -> None:
        if self._shut:  # the C++ pool was freed; a call would be UAF
            from ..core.errors import Error, HpxError
            raise HpxError(Error.invalid_status, "pool is shut down")
        from ..runtime.threadpool import notify_submit
        notify_submit([(fn, args)])
        with self._tasks_lock:
            tid = self._next_id
            self._next_id += 1
            self._tasks[tid] = (fn, args, kwargs)
        self._lib.hpxrt_pool_submit(self._handle, self._tramp, tid)

    def submit_many(self, tasks) -> None:
        """Batch fire-and-forget: `tasks` is a sequence of
        (fn, args, kwargs) triples, registered under contiguous ids with
        ONE lock acquisition and handed to the scheduler with ONE C
        call (hpxrt_pool_submit_many) — the fan-out path that amortizes
        the per-task interpreter/ABI overhead."""
        if self._shut:
            from ..core.errors import Error, HpxError
            raise HpxError(Error.invalid_status, "pool is shut down")
        tasks = list(tasks)
        if not tasks:
            return
        if not hasattr(self._lib, "hpxrt_pool_submit_many"):
            for fn, args, kwargs in tasks:       # stale .so fallback
                self.submit(fn, *args, **kwargs)
            return
        from ..runtime.threadpool import notify_submit
        notify_submit((fn, args) for fn, args, _ in tasks)
        with self._tasks_lock:
            start = self._next_id
            self._next_id += len(tasks)
            for i, t in enumerate(tasks):
                self._tasks[start + i] = t
        self._lib.hpxrt_pool_submit_many(self._handle, self._tramp,
                                         start, len(tasks))

    def help_one(self) -> bool:
        if self._shut:
            return False
        # depth-bounded like the Python pool: every nested help crosses
        # the C stack through the ctypes trampoline, so unbounded
        # nesting overflows long before Python's recursion limit
        from ..runtime.threadpool import enter_help, exit_help
        if not enter_help():
            return False
        try:
            return bool(self._lib.hpxrt_pool_help_one(self._handle))
        finally:
            exit_help()

    def in_worker(self) -> bool:
        if self._shut:
            return False
        return bool(self._lib.hpxrt_pool_in_worker(self._handle))

    def _stats_locked(self) -> dict:
        """Caller holds _shutdown_lock (or is shutdown() itself)."""
        if self._shut:
            return dict(self._last_stats, shutdown=True)
        self._last_stats = {
            "executed": int(self._lib.hpxrt_pool_executed(self._handle)),
            "stolen": int(self._lib.hpxrt_pool_stolen(self._handle)),
            "pending": int(self._lib.hpxrt_pool_pending(self._handle)),
            "threads": self._n,
        }
        if hasattr(self._lib, "hpxrt_pool_idle"):
            self._last_stats["idle"] = int(
                self._lib.hpxrt_pool_idle(self._handle))
        return self._last_stats

    def stats(self) -> dict:
        # under the shutdown lock: counter callbacks poll stats() from
        # arbitrary threads, and an unlocked read could dereference the
        # C++ pool mid-free (same hazard queue_length documents)
        with self._shutdown_lock:
            return self._stats_locked()

    def shutdown(self, wait: bool = True) -> None:
        # wait is accepted for interface parity with WorkStealingPool;
        # the native pool always joins its workers before freeing.
        if self._shut:
            return
        if self._handle is not None and self.in_worker():
            # a pool cannot join itself: pthread_join(self) aborts the
            # process. Hand the join to a fresh thread (continuations
            # commonly fire on the last worker that completed a future).
            import threading as _t
            _t.Thread(target=self.shutdown, name="pool-reaper",
                      daemon=True).start()
            return
        # the reaper hand-off means concurrent shutdown callers are
        # expected (reaper + atexit/__del__): serialize the
        # check-then-free so the native shutdown runs exactly once.
        # The lock covers ONLY the state flip — holding it across the
        # C++ join would deadlock any pool TASK that reads stats()
        # (worker blocks on the lock, join waits for the worker).
        with self._shutdown_lock:
            if self._shut:
                return
            self._stats_locked()  # snapshot final counters (lock held)
            self._shut = True
            handle, self._handle = self._handle, None
        # workers in _worker_of must not help a dead pool; stats/
        # queue_length callers now see _shut and never touch `handle`
        self._lib.hpxrt_pool_shutdown(handle)

    def __del__(self) -> None:  # best-effort; explicit shutdown preferred
        try:
            self.shutdown()
        except Exception:
            pass


# -- Chase-Lev lock-free deque binding --------------------------------------

def _bind_cldeque(lib: ctypes.CDLL) -> None:
    if getattr(lib, "_cld_bound", False):
        return
    for sym in ("hpxrt_cldeque_create", "hpxrt_cldeque_push",
                "hpxrt_cldeque_take", "hpxrt_cldeque_steal",
                "hpxrt_cldeque_size", "hpxrt_cldeque_destroy"):
        if not hasattr(lib, sym):
            raise RuntimeError(
                f"libhpx_tpu_rt.so is stale (missing symbol {sym}); "
                f"rebuild it: make -C {_HERE} clean && make -C {_HERE}")
    lib.hpxrt_cldeque_create.restype = ctypes.c_void_p
    lib.hpxrt_cldeque_push.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.hpxrt_cldeque_take.restype = ctypes.c_void_p
    lib.hpxrt_cldeque_take.argtypes = [ctypes.c_void_p]
    lib.hpxrt_cldeque_steal.restype = ctypes.c_void_p
    lib.hpxrt_cldeque_steal.argtypes = [ctypes.c_void_p]
    lib.hpxrt_cldeque_size.restype = ctypes.c_long
    lib.hpxrt_cldeque_size.argtypes = [ctypes.c_void_p]
    lib.hpxrt_cldeque_destroy.argtypes = [ctypes.c_void_p]
    lib._cld_bound = True


class ChaseLevDeque:
    """Lock-free work-stealing deque of nonzero ints (C Chase-Lev).

    push()/take() are OWNER-thread operations; steal() may be called
    from any thread (ctypes releases the GIL during the call, so Python
    threads genuinely race the lock-free C code). Items are opaque
    pointer-sized nonzero ints — 0 means empty.
    """

    def __init__(self) -> None:
        lib = native_lib()
        if lib is None:
            raise RuntimeError("native runtime library unavailable")
        _bind_cldeque(lib)
        self._lib = lib
        self._h = lib.hpxrt_cldeque_create()
        # close() must not free the C object under a thread that is
        # INSIDE a (GIL-released) deque call: ops register in-flight
        # around the call — the C calls themselves still race lock-free
        # — and close waits for quiescence before destroying.
        self._cv = threading.Condition()
        self._inflight = 0

    def _enter(self):
        with self._cv:
            if self._h is None:
                raise RuntimeError("deque is closed")
            self._inflight += 1
            return self._h

    def _exit(self) -> None:
        with self._cv:
            self._inflight -= 1
            if self._inflight == 0:
                self._cv.notify_all()

    def push(self, item: int) -> None:
        if item == 0:
            raise ValueError("0 is the empty sentinel")
        h = self._enter()
        try:
            self._lib.hpxrt_cldeque_push(h, item)
        finally:
            self._exit()

    def take(self) -> Optional[int]:
        h = self._enter()
        try:
            v = self._lib.hpxrt_cldeque_take(h)
        finally:
            self._exit()
        return None if not v else int(v)

    def steal(self) -> Optional[int]:
        h = self._enter()
        try:
            v = self._lib.hpxrt_cldeque_steal(h)
        finally:
            self._exit()
        return None if not v else int(v)

    def __len__(self) -> int:
        h = self._enter()
        try:
            return int(self._lib.hpxrt_cldeque_size(h))
        finally:
            self._exit()

    def close(self) -> None:
        with self._cv:
            if self._h is None:
                return
            self._cv.wait_for(lambda: self._inflight == 0)
            h, self._h = self._h, None
        self._lib.hpxrt_cldeque_destroy(h)

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:
            pass


# -- TCP parcel transport binding -------------------------------------------

_NET_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_int,
                           ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64)


def _bind_net(lib: ctypes.CDLL) -> None:
    if getattr(lib, "_net_bound", False):
        return
    # symbol probe BEFORE binding: a stale prebuilt .so (built from older
    # sources, e.g. copied between checkouts — the Makefile's always-
    # remake only covers in-tree builds) would otherwise surface as a
    # bare AttributeError deep inside NetEndpoint.__init__
    for sym in ("hpxrt_net_create", "hpxrt_net_create2",
                "hpxrt_net_create3"):
        if not hasattr(lib, sym):
            raise RuntimeError(
                f"libhpx_tpu_rt.so is stale (missing symbol {sym}); "
                f"rebuild it: make -C {_HERE} clean && make -C {_HERE}")
    lib.hpxrt_net_create.restype = ctypes.c_void_p
    lib.hpxrt_net_create.argtypes = [ctypes.c_uint16]
    lib.hpxrt_net_create2.restype = ctypes.c_void_p
    lib.hpxrt_net_create2.argtypes = [ctypes.c_uint16, ctypes.c_int]
    lib.hpxrt_net_create3.restype = ctypes.c_void_p
    lib.hpxrt_net_create3.argtypes = [ctypes.c_uint16, ctypes.c_char_p]
    lib.hpxrt_net_port.restype = ctypes.c_uint16
    lib.hpxrt_net_port.argtypes = [ctypes.c_void_p]
    lib.hpxrt_net_set_callback.argtypes = [ctypes.c_void_p, _NET_CB,
                                           ctypes.c_void_p]
    lib.hpxrt_net_start.argtypes = [ctypes.c_void_p]
    lib.hpxrt_net_connect.restype = ctypes.c_int
    lib.hpxrt_net_connect.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_uint16]
    lib.hpxrt_net_send.restype = ctypes.c_int
    lib.hpxrt_net_send.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                   ctypes.c_char_p, ctypes.c_uint64]
    lib.hpxrt_net_destroy.argtypes = [ctypes.c_void_p]
    lib._net_bound = True


class NetEndpoint:
    """Framed TCP endpoint over the native epoll transport.

    on_message(peer_id, bytes) is invoked on the IO thread (under the
    GIL); keep it cheap — the parcel layer enqueues to the task pool.
    """

    def __init__(self, port: int = 0,
                 on_message: Optional[Callable[[int, bytes], None]] = None,
                 bind: str = "127.0.0.1"):
        lib = native_lib()
        if lib is None:
            raise RuntimeError("native runtime library unavailable")
        _bind_net(lib)
        self._lib = lib
        # the native path takes IPv4 literals only; resolve names here
        import socket as _s
        try:
            _s.inet_pton(_s.AF_INET, bind)
        except OSError:
            bind = _s.getaddrinfo(bind, port, _s.AF_INET,
                                  _s.SOCK_STREAM)[0][4][0]
        self._h = lib.hpxrt_net_create3(port, bind.encode())
        if not self._h:
            raise OSError(f"cannot listen on {bind}:{port}")
        self.on_message = on_message
        # surface the epoll thread in the io_service registry (the
        # reference's "parcel" helper pool) for io_pool_names()/counters
        try:
            from ..runtime.io_service import register_external_pool
            register_external_pool("parcel", 1,
                                   "native/net.cpp epoll thread")
        except Exception:  # noqa: BLE001 — observability only
            pass

        def _cb(_user, peer_id, data, length):
            payload = ctypes.string_at(data, length)
            handler = self.on_message
            if handler is not None:
                handler(peer_id, payload)

        self._cb = _NET_CB(_cb)
        lib.hpxrt_net_set_callback(self._h, self._cb, None)
        lib.hpxrt_net_start(self._h)
        self._closed = False

    @property
    def port(self) -> int:
        if self._closed:
            raise OSError("endpoint closed")
        return int(self._lib.hpxrt_net_port(self._h))

    def connect(self, host: str, port: int) -> int:
        if self._closed:
            raise OSError("endpoint closed")
        # the native path takes IPv4 literals only (inet_pton); resolve
        # DNS names (multi-node: hpx.parcel.address=nodename) here
        import socket
        try:
            socket.inet_pton(socket.AF_INET, host)
        except OSError:
            host = socket.getaddrinfo(
                host, port, socket.AF_INET, socket.SOCK_STREAM)[0][4][0]
        pid = self._lib.hpxrt_net_connect(self._h, host.encode(), port)
        if pid < 0:
            raise OSError(f"connect to {host}:{port} failed")
        return pid

    def send(self, peer_id: int, data: bytes) -> None:
        if self._closed:
            raise OSError("endpoint closed")
        if self._lib.hpxrt_net_send(self._h, peer_id, data, len(data)) != 0:
            raise OSError(f"send to peer {peer_id} failed")

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._lib.hpxrt_net_destroy(self._h)
