"""Distributed unordered map.

Reference analog: components/containers/unordered (`hpx::unordered_map`:
a hash map whose buckets are partition COMPONENTS spread over
localities; keys route by hash — SURVEY.md §2.4 inventory).

Built directly on the components layer (dist/components.py): one
partition component per participating locality; a stable cross-process
key hash picks the partition; clients ship through AGAS basenames so
every locality can connect to the same named map. Values travel through
the parcel serializer, so jax.Arrays are fine as values (they move as
numpy and are restored on the reader's device) — but BULK array data
belongs in a PartitionedVector; this container is the control-plane
key/value store, as in the reference.

Keys must hash identically in every process: supported key types are
int, str, bytes, bool, None, and (nested) tuples thereof (Python's
builtin hash() is salted per process, so we use a content hash).
"""

from __future__ import annotations

import hashlib
import struct
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from ..core.errors import Error, HpxError
from ..dist.components import (Client, Component, find_from_basename, new_,
                               register_component_type,
                               register_with_basename)
from ..futures.combinators import when_all
from ..futures.future import Future, make_ready_future

__all__ = ["UnorderedMap", "stable_hash"]


def _hash_bytes(key: Any, h) -> None:
    if key is None:
        h.update(b"\x00N")
    elif isinstance(key, bool):
        h.update(b"\x00B" + (b"1" if key else b"0"))
    elif isinstance(key, int):
        h.update(b"\x00I" + str(key).encode())
    elif isinstance(key, str):
        b = key.encode("utf-8")
        h.update(b"\x00S" + struct.pack("<Q", len(b)) + b)
    elif isinstance(key, bytes):
        h.update(b"\x00Y" + struct.pack("<Q", len(key)) + key)
    elif isinstance(key, tuple):
        h.update(b"\x00T" + struct.pack("<Q", len(key)))
        for k in key:
            _hash_bytes(k, h)
    else:
        raise HpxError(Error.bad_parameter,
                       f"unhashable-across-processes key type: "
                       f"{type(key).__name__} (use int/str/bytes/tuple)")


def stable_hash(key: Any) -> int:
    """Process-independent hash for supported key types."""
    h = hashlib.blake2b(digest_size=8)
    _hash_bytes(key, h)
    return int.from_bytes(h.digest(), "little")


@register_component_type
class _MapPartition(Component):
    """One bucket-set; lives on one locality (the partition server)."""

    def __init__(self) -> None:
        self.data: Dict[Any, Any] = {}

    def get(self, key: Any) -> Any:
        try:
            return self.data[key]
        except KeyError:
            raise HpxError(Error.bad_parameter,
                           f"key not found: {key!r}") from None

    def get_or(self, key: Any, default: Any) -> Any:
        return self.data.get(key, default)

    def set(self, key: Any, value: Any) -> None:
        self.data[key] = value

    def update(self, kvs: List[Tuple[Any, Any]]) -> None:
        self.data.update(kvs)

    def erase(self, key: Any) -> bool:
        return self.data.pop(key, _MISSING) is not _MISSING

    def contains(self, key: Any) -> bool:
        return key in self.data

    def size(self) -> int:
        return len(self.data)

    def items(self) -> List[Tuple[Any, Any]]:
        return list(self.data.items())

    def clear(self) -> int:
        n = len(self.data)
        self.data.clear()
        return n


class _Missing:
    __slots__ = ()


_MISSING = _Missing()


class UnorderedMap:
    """hpx::unordered_map analog.

    Create on ONE locality (partitions are placed round-robin over the
    given localities), publish with register_as, connect elsewhere with
    connect_to. All value-returning calls have future (`*_async`) and
    blocking spellings, like the reference's client API.
    """

    def __init__(self, localities: Optional[Sequence[int]] = None,
                 _parts: Optional[List[Client]] = None,
                 placement: Optional[Any] = None,
                 num_partitions: Optional[int] = None) -> None:
        if _parts is not None:
            self._parts = _parts
            return
        if num_partitions is not None and int(num_partitions) < 1:
            raise HpxError(Error.bad_parameter,
                           f"num_partitions={num_partitions} < 1")
        if placement is not None:
            # binpacked()/colocated(...) choose the partition hosts —
            # the reference's binpacking_distribution_policy applied to
            # a partitioned container
            if localities is not None:
                raise HpxError(
                    Error.bad_parameter,
                    "pass candidate localities to the policy itself "
                    "(binpacked(localities=...)), not both placement= "
                    "and localities=")
            if num_partitions is None:
                from ..dist.runtime import get_num_localities
                n = get_num_localities()
            else:
                n = int(num_partitions)
            locs = placement.resolve(
                n, _MapPartition.__dict__.get("_component_type_name"))
        else:
            if localities is None:
                from ..dist.runtime import find_all_localities
                localities = find_all_localities()
            base = list(localities)
            if not base:
                raise HpxError(Error.bad_parameter, "no localities given")
            if num_partitions is None:
                locs = base
            else:
                # partition count independent of locality count (the
                # reference's container_layout(n, localities)):
                # round-robin n partitions over the given localities
                locs = [base[i % len(base)]
                        for i in range(int(num_partitions))]
        if not locs:
            raise HpxError(Error.bad_parameter, "no localities given")
        futs = [new_(_MapPartition, loc) for loc in locs]
        self._parts = [f.get(timeout=30.0) for f in futs]

    # -- routing ------------------------------------------------------------
    def _part(self, key: Any) -> Client:
        return self._parts[stable_hash(key) % len(self._parts)]

    @property
    def num_partitions(self) -> int:
        return len(self._parts)

    # -- element access ------------------------------------------------------
    def set_async(self, key: Any, value: Any) -> Future:
        return self._part(key).call("set", key, value)

    def set(self, key: Any, value: Any) -> None:
        self.set_async(key, value).get()

    def get_async(self, key: Any) -> Future:
        return self._part(key).call("get", key)

    def get(self, key: Any, default: Any = _MISSING) -> Any:
        if default is _MISSING:
            return self.get_async(key).get()
        return self._part(key).call("get_or", key, default).get()

    def __setitem__(self, key: Any, value: Any) -> None:
        self.set(key, value)

    def __getitem__(self, key: Any) -> Any:
        try:
            return self.get_async(key).get()
        except HpxError as e:
            # only the partition's key-not-found maps to KeyError; a
            # timeout/network failure must NOT masquerade as a missing
            # key (callers treat KeyError as "compute the default")
            if e.code == Error.bad_parameter:
                raise KeyError(key) from e
            raise

    def contains_async(self, key: Any) -> Future:
        return self._part(key).call("contains", key)

    def __contains__(self, key: Any) -> bool:
        return bool(self.contains_async(key).get())

    def erase_async(self, key: Any) -> Future:
        return self._part(key).call("erase", key)

    def erase(self, key: Any) -> bool:
        return bool(self.erase_async(key).get())

    def __delitem__(self, key: Any) -> None:
        if not self.erase(key):
            raise KeyError(key)

    # -- bulk ----------------------------------------------------------------
    def update(self, mapping: Any) -> Future:
        """Batched multi-set: one parcel per touched partition."""
        items = mapping.items() if hasattr(mapping, "items") else mapping
        per: Dict[int, List[Tuple[Any, Any]]] = {}
        for k, v in items:
            per.setdefault(stable_hash(k) % len(self._parts),
                           []).append((k, v))
        futs = [self._parts[i].call("update", kvs)
                for i, kvs in per.items()]
        if not futs:
            return make_ready_future(None)
        return when_all(futs).then(
            lambda f: [x.get() for x in f.get()] and None)

    def size_async(self) -> Future:
        futs = [p.call("size") for p in self._parts]
        return when_all(futs).then(
            lambda f: sum(x.get() for x in f.get()))

    def size(self) -> int:
        return self.size_async().get()

    def __len__(self) -> int:
        return self.size()

    def items(self) -> List[Tuple[Any, Any]]:
        futs = [p.call("items") for p in self._parts]
        out: List[Tuple[Any, Any]] = []
        for f in when_all(futs).get():
            out.extend(f.get())
        return out

    def keys(self) -> List[Any]:
        return [k for k, _v in self.items()]

    def values(self) -> List[Any]:
        return [v for _k, v in self.items()]

    def clear(self) -> int:
        futs = [p.call("clear") for p in self._parts]
        return sum(f.get() for f in when_all(futs).get())

    # -- lifetime / naming ---------------------------------------------------
    def register_as(self, name: str) -> Future:
        """Publish partition clients under a basename (reference:
        HPX_REGISTER_UNORDERED_MAP + register_with_basename)."""
        futs = [register_with_basename(f"unordered/{name}", p, i)
                for i, p in enumerate(self._parts)]
        futs.append(register_with_basename(
            f"unordered/{name}/nparts", len(self._parts)))
        return when_all(futs).then(
            lambda f: [x.get() for x in f.get()] and None)

    @classmethod
    def connect_to(cls, name: str) -> "UnorderedMap":
        n = find_from_basename(f"unordered/{name}/nparts").get(timeout=30.0)
        parts = [find_from_basename(f"unordered/{name}", i).get(timeout=30.0)
                 for i in range(int(n))]
        return cls(_parts=parts)

    def free(self) -> Future:
        futs = [p.free() for p in self._parts]
        return when_all(futs).then(
            lambda f: [x.get() for x in f.get()] and None)

    def __repr__(self) -> str:
        return f"UnorderedMap(partitions={len(self._parts)})"
