"""partitioned_vector: the distributed container.

Reference analog: components/containers/partitioned_vector — a vector
split into partition components spread over localities per a distribution
policy, with segmented iterators and named registration for multi-locality
access (SURVEY.md §2.4).

TPU-first design (SURVEY.md §7): a PartitionedVector is a mutable HANDLE
over an immutable sharded jax.Array. The distribution policy fixes the
NamedSharding; XLA/GSPMD owns byte placement and inserts any collectives.
"Segments" are logical (index-range, device) views, not separate objects —
there is no per-partition component server because the single-controller
model addresses every shard directly. Segmented algorithms (algo/
segmented.py) dispatch whole-container ops as ONE sharded XLA program,
which is the shard_map/pjit equivalent of HPX's per-segment remote asyncs.

Uneven sizes: jax shardings want divisible extents, so the backing array
is padded up to a multiple of the partition count; `size` stays logical
and `valid_array()` returns the unpadded prefix (a lazy device slice; a
no-op view when the size divides evenly — the performance case).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Iterator, Optional, Sequence, Tuple, Union

from ..dist.distribution_policies import ContainerLayout, default_layout


@dataclass(frozen=True)
class Segment:
    """One logical partition: [begin, end) and where it lives.

    The analog of HPX's segment iterator position (partitioned_vector_
    segmented_iterator). With fewer partitions than devices along the
    axis a segment spans several devices — `devices` lists them all in
    axis order; `device` is the first (where the segment starts).
    """
    index: int
    begin: int
    end: int
    devices: Tuple[Any, ...]

    @property
    def device(self) -> Any:
        return self.devices[0]

    def __len__(self) -> int:
        return self.end - self.begin


class PartitionedVectorView:
    """A contiguous sub-range view (partitioned_vector_view analog).

    Used for SPMD-style sub-range access; algorithms accept views and
    operate on the underlying device slice.
    """

    def __init__(self, pv: "PartitionedVector", begin: int, end: int) -> None:
        begin = max(0, min(begin, pv.size))
        end = max(begin, min(end, pv.size))
        self.pv = pv
        self.begin = begin
        self.end = end

    def array(self):
        return self.pv.valid_array()[self.begin:self.end]

    def __len__(self) -> int:
        return self.end - self.begin

    def __getitem__(self, i):
        if isinstance(i, slice):
            start, stop, step = i.indices(len(self))
            if step != 1:
                raise IndexError("views are contiguous (step must be 1)")
            return PartitionedVectorView(
                self.pv, self.begin + start, self.begin + stop)
        return self.pv[self.begin + self._check(i)]

    def _check(self, i: int) -> int:
        if i < 0:
            i += len(self)
        if not 0 <= i < len(self):
            raise IndexError(i)
        return i

    def to_numpy(self):
        import numpy as np
        return np.asarray(self.array())

    def __repr__(self) -> str:
        return f"<PartitionedVectorView [{self.begin}, {self.end}) of {self.pv!r}>"


class PartitionedVector:
    """hpx::partitioned_vector<T> analog over a sharded jax.Array."""

    def __init__(self, size: int, value: Any = 0, dtype: Any = None,
                 layout: Optional[ContainerLayout] = None) -> None:
        import jax.numpy as jnp
        self._layout = layout or default_layout()
        self._size = int(size)
        if dtype is None:
            dtype = jnp.asarray(value).dtype if value is not None \
                else jnp.float32
        padded = self._padded_size(self._size, self._layout)
        import jax
        self._data = jax.device_put(
            jnp.full((padded,), value, dtype=dtype),
            self._layout.sharding())

    # -- construction --------------------------------------------------------
    @staticmethod
    def _padded_size(n: int, layout: ContainerLayout) -> int:
        p = max(layout.num_partitions, layout.axis_size)
        return ((max(n, 1) + p - 1) // p) * p

    @classmethod
    def from_array(cls, arr: Any,
                   layout: Optional[ContainerLayout] = None
                   ) -> "PartitionedVector":
        """Build from an existing 1-D array (host or device)."""
        import jax
        import jax.numpy as jnp
        layout = layout or default_layout()
        arr = jnp.asarray(arr)
        if arr.ndim != 1:
            raise ValueError("partitioned_vector is 1-D; got shape "
                             f"{arr.shape}")
        self = cls.__new__(cls)
        self._layout = layout
        self._size = int(arr.shape[0])
        padded = cls._padded_size(self._size, layout)
        if padded != self._size:
            arr = jnp.pad(arr, (0, padded - self._size))
        self._data = jax.device_put(arr, layout.sharding())
        return self

    # -- basic surface -------------------------------------------------------
    @property
    def size(self) -> int:
        return self._size

    def __len__(self) -> int:
        return self._size

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def layout(self) -> ContainerLayout:
        return self._layout

    @property
    def mesh(self):
        return self._layout.mesh

    @property
    def num_partitions(self) -> int:
        return self._layout.num_partitions

    @property
    def data(self):
        """The backing (padded) sharded jax.Array."""
        return self._data

    def valid_array(self):
        """The logical contents as a device array (lazy slice if padded)."""
        if self._data.shape[0] == self._size:
            return self._data
        return self._data[:self._size]

    def to_numpy(self):
        import numpy as np
        return np.asarray(self.valid_array())

    # -- element access (get_value/set_value analogs) ------------------------
    def get(self, i: int) -> Any:
        """Synchronous element fetch (hpx::partitioned_vector::get_value)."""
        return self._data[self._check(i)].item()

    def get_async(self, i: int):
        """get_value(launch::async) analog: Future of the element."""
        from ..futures.future import make_ready_future
        v = self._data[self._check(i)]
        return make_ready_future(v)

    def set(self, i: int, value: Any) -> None:
        """set_value analog: functional update swapped into the handle."""
        self._data = self._data.at[self._check(i)].set(value)

    def _check(self, i: int) -> int:
        if i < 0:
            i += self._size
        if not 0 <= i < self._size:
            raise IndexError(i)
        return i

    def __getitem__(self, i: Union[int, slice]):
        if isinstance(i, slice):
            start, stop, step = i.indices(self._size)
            if step != 1:
                raise IndexError("views are contiguous (step must be 1)")
            return PartitionedVectorView(self, start, stop)
        return self.get(i)

    def __setitem__(self, i: int, value: Any) -> None:
        self.set(i, value)

    def view(self, begin: int = 0,
             end: Optional[int] = None) -> PartitionedVectorView:
        return PartitionedVectorView(
            self, begin, self._size if end is None else end)

    # -- segments (segmented iterator surface) -------------------------------
    def segments(self) -> Sequence[Segment]:
        """Logical partitions with their devices, in index order."""
        npart = self.num_partitions
        padded = self._data.shape[0]
        chunk = padded // npart
        axis_devs = self._axis_devices()
        per_dev = padded // len(axis_devs)
        out = []
        for k in range(npart):
            pb, pe = k * chunk, (k + 1) * chunk   # padded coords
            # NamedSharding places contiguous blocks: device d along the
            # axis holds [d*per_dev, (d+1)*per_dev) of the padded extent;
            # a segment spans every device its padded range overlaps
            d0, d1 = pb // per_dev, (pe - 1) // per_dev
            devs = tuple(axis_devs[d] for d in range(d0, d1 + 1))
            b, e = min(pb, self._size), min(pe, self._size)
            out.append(Segment(k, b, e, devs))
        return out

    def _axis_devices(self):
        mesh = self._layout.mesh
        axis_index = mesh.axis_names.index(self._layout.axis)
        import numpy as np
        devs = np.moveaxis(np.asarray(mesh.devices), axis_index, 0)
        devs = devs.reshape(devs.shape[0], -1)
        return [devs[k, 0] for k in range(devs.shape[0])]

    def __iter__(self) -> Iterator[Any]:
        import numpy as np
        return iter(np.asarray(self.valid_array()))

    # -- named registration (AGAS symbol namespace) --------------------------
    def register_as(self, name: str):
        """HPX_REGISTER_PARTITIONED_VECTOR + register_as analog: publish
        this handle under a global name (returns Future[bool])."""
        from ..dist import agas
        return agas.register_name(f"containers/{name}", self)

    @classmethod
    def connect_to(cls, name: str, wait: bool = True) -> "PartitionedVector":
        """connect_to analog: look up a registered vector by name."""
        from ..dist import agas
        return agas.resolve_name(f"containers/{name}", wait=wait).get()

    def unregister(self, name: str):
        from ..dist import agas
        return agas.unregister_name(f"containers/{name}")

    # -- misc ----------------------------------------------------------------
    def copy(self) -> "PartitionedVector":
        out = PartitionedVector.__new__(PartitionedVector)
        out._layout = self._layout
        out._size = self._size
        out._data = self._data
        return out

    def __repr__(self) -> str:
        return (f"<partitioned_vector size={self._size} dtype={self.dtype} "
                f"partitions={self.num_partitions} axis="
                f"'{self._layout.axis}'>")
