"""Distributed containers (components/containers analog)."""

from .partitioned_vector import (  # noqa: F401
    PartitionedVector,
    PartitionedVectorView,
    Segment,
)
from .unordered_map import UnorderedMap, stable_hash  # noqa: F401
