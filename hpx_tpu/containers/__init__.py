"""Distributed containers (components/containers analog)."""

from .partitioned_vector import (  # noqa: F401
    PartitionedVector,
    PartitionedVectorView,
    Segment,
)
