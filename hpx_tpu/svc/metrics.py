"""The fleet metrics plane: log-bucketed latency histograms,
per-request lifecycle timelines, and Prometheus text exposition.

Scalars (``performance_counters``) answer "how much"; the adaptive
executor (ROADMAP item 3) and overload reporting (item 5) need "how
slow, at which percentile" — live *distributions* that survive the
disagg/fleet hop.  Three pieces:

``HistogramCounter``
    A log2-bucketed histogram (DDSketch/HdrHistogram family): bucket i
    covers ``[lo * gamma**(i-1), lo * gamma**i)`` with
    ``gamma = 2 ** (1 / subbuckets)``, so ``record()`` is one
    ``math.log`` plus a GIL-atomic list increment, memory is O(buckets)
    no matter how many samples land, and ``quantile(q)`` answers with
    relative error bounded by ``gamma**0.5 - 1`` (~4.4% at the default
    8 subbuckets/octave).  Histograms with the same layout ``merge()``
    by vector addition — exact, associative, commutative — which is how
    per-worker distributions become ONE fleet-wide distribution without
    shipping samples.  It IS a ``performance_counters.Counter`` (value
    = running mean), and :func:`register_histogram` additionally
    derives ``.../p50|p95|p99`` callback counters so quantiles are
    queryable through the ordinary counter surface.

``RequestTimeline``
    A bounded, rid-keyed event log (submit → place → prefill start →
    KV transfer → first token → retire) with drop-oldest eviction —
    the per-request view the aggregate histograms deliberately discard.

``render_prometheus()``
    Text exposition of the whole counter registry: histograms as
    cumulative ``_bucket{le=...}`` series plus ``_sum``/``_count``,
    everything else as gauges.

Knobs (``hpx.metrics.*``, declared in core/config_schema.py): bucket
range ``hist_lo``/``hist_hi``, resolution ``hist_subbuckets``, derived
``quantiles``, and ``timeline_capacity``.
"""

from __future__ import annotations

import math
import time
from collections import OrderedDict
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from . import performance_counters as pc

__all__ = [
    "HistogramCounter",
    "RequestTimeline",
    "LATENCY_KEYS",
    "latency_histograms",
    "register_histogram",
    "quantile_label",
    "configured_quantiles",
    "render_prometheus",
    "registry_snapshot",
    "timeline_dropped_entries",
    "reset_timeline_dropped",
    "PROM_CONTENT_TYPE",
    "OPENMETRICS_CONTENT_TYPE",
    "negotiate_exposition",
]

# the latency families threaded through ContinuousServer / DisaggRouter
# / FleetRouter (one HistogramCounter each, per worker; fleet-wide =
# merge() of the per-worker set)
LATENCY_KEYS = ("ttft", "queue_wait", "transfer", "decode_stall", "e2e")


def _cfg():
    from ..core.config import runtime_config
    return runtime_config()


def configured_quantiles() -> Tuple[float, ...]:
    """The derived-quantile set (``hpx.metrics.quantiles``)."""
    raw = _cfg().get("hpx.metrics.quantiles", "0.5,0.95,0.99")
    out = []
    for part in str(raw).split(","):
        part = part.strip()
        if part:
            out.append(float(part))
    return tuple(out)


def quantile_label(q: float) -> str:
    """0.5 → "p50", 0.95 → "p95", 0.999 → "p99.9"."""
    return f"p{round(q * 100.0, 4):g}"


class _Timer:
    """Context manager minted by zero-arg :meth:`HistogramCounter.record`;
    records elapsed seconds on exit.  Discarding it records nothing —
    hpxlint HPX016 flags that."""

    __slots__ = ("_hist", "_t0", "seconds")

    def __init__(self, hist: "HistogramCounter") -> None:
        self._hist = hist
        self._t0 = 0.0
        self.seconds: Optional[float] = None

    def __enter__(self) -> "_Timer":
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc) -> bool:
        self.seconds = time.monotonic() - self._t0
        self._hist.record(self.seconds)
        return False


class HistogramCounter(pc.Counter):
    """Log-bucketed histogram with bounded-relative-error quantiles.

    ``record(v)`` is lock-free: one bucket-index computation plus plain
    int/float updates, each atomic under the GIL (same best-effort
    discipline as ``Tracer.dropped`` — a torn multi-field update can
    skew ``sum`` by one sample, never corrupt the structure).
    ``record()`` with no value returns a timer context manager.

    Bucket layout is fixed at construction (``lo``, ``hi``,
    ``subbuckets`` per octave); values below ``lo`` land in an
    underflow bucket, at/above ``hi`` in an overflow bucket, both still
    counted in ``count``/``sum``/``min``/``max``.  Only histograms with
    identical layouts ``merge()``.
    """

    def __init__(self, lo: Optional[float] = None,
                 hi: Optional[float] = None,
                 subbuckets: Optional[int] = None) -> None:
        if lo is None or hi is None or subbuckets is None:
            cfg = _cfg()
            lo = cfg.get_float("hpx.metrics.hist_lo", 1e-6) \
                if lo is None else lo
            hi = cfg.get_float("hpx.metrics.hist_hi", 1e4) \
                if hi is None else hi
            subbuckets = cfg.get_int("hpx.metrics.hist_subbuckets", 8) \
                if subbuckets is None else subbuckets
        if not (0.0 < lo < hi):
            raise ValueError(f"need 0 < lo < hi, got lo={lo} hi={hi}")
        if subbuckets < 1:
            raise ValueError(f"subbuckets must be >= 1: {subbuckets}")
        self.lo = float(lo)
        self.hi = float(hi)
        self.subbuckets = int(subbuckets)
        self._log_gamma = math.log(2.0) / self.subbuckets
        self.gamma = math.exp(self._log_gamma)
        self._nb = int(math.ceil(
            math.log(self.hi / self.lo) / self._log_gamma))
        # [0] underflow | [1.._nb] log buckets | [_nb+1] overflow
        self.counts: List[int] = [0] * (self._nb + 2)
        self.count = 0
        self.sum = 0.0
        self.vmin = math.inf
        self.vmax = -math.inf
        # tail-bucket exemplar reservoir (svc/exemplars), attached only
        # when hpx.obs.exemplars is on — None keeps record() at its
        # pre-observability cost (one attr load + is-None test)
        self._ex = None

    # -- recording ----------------------------------------------------

    def _index(self, v: float) -> int:
        if v < self.lo:
            return 0
        if v >= self.hi:
            return self._nb + 1
        i = int(math.log(v / self.lo) / self._log_gamma) + 1
        return min(max(i, 1), self._nb)

    def record(self, value: Optional[float] = None,
               rid: Any = None) -> Optional[_Timer]:
        """Record one sample; with no argument, return a context
        manager that records its elapsed seconds on exit.  ``rid``
        (optional) attributes the sample: when an exemplar reservoir
        is attached and the sample lands in a tail bucket, the rid is
        captured alongside value/wall-ts/span so the bucket resolves
        back to a RequestTimeline entry."""
        if value is None:
            return _Timer(self)
        v = float(value)
        i = self._index(v)
        self.counts[i] += 1
        self.count += 1
        self.sum += v
        if v < self.vmin:
            self.vmin = v
        if v > self.vmax:
            self.vmax = v
        ex = self._ex
        if ex is not None:
            ex.offer(i, v, rid)
        return None

    # -- reading ------------------------------------------------------

    def relative_error_bound(self) -> float:
        """Worst-case relative quantile error for in-range values: the
        geometric bucket midpoint is at most ``gamma**0.5`` away from
        any sample in the bucket."""
        return math.sqrt(self.gamma) - 1.0

    def bucket_upper(self, i: int) -> float:
        """Upper bound of bucket ``i`` (``lo`` for underflow, ``inf``
        for overflow)."""
        if i <= 0:
            return self.lo
        if i > self._nb:
            return math.inf
        return self.lo * math.exp(i * self._log_gamma)

    def quantile(self, q: float) -> float:
        """Nearest-rank quantile estimate, clamped into the observed
        [min, max] (so constant samples answer exactly); 0.0 when
        empty."""
        if not self.count:
            return 0.0
        q = min(max(q, 0.0), 1.0)
        target = max(1, math.ceil(q * self.count))
        cum = 0
        est = 0.0
        for i, c in enumerate(self.counts):
            cum += c
            if cum >= target:
                if i == 0:
                    est = self.vmin if math.isfinite(self.vmin) \
                        else self.lo
                elif i > self._nb:
                    est = self.vmax
                else:
                    est = self.lo * math.exp((i - 0.5) * self._log_gamma)
                break
        return min(max(est, self.vmin), self.vmax)

    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    # -- merge / snapshot ---------------------------------------------

    def _layout(self) -> Tuple[float, float, int]:
        return (self.lo, self.hi, self.subbuckets)

    def merge(self, other: "HistogramCounter") -> "HistogramCounter":
        """Return a NEW histogram holding both inputs' samples (vector
        addition of bucket counts — exact, associative, commutative).
        Neither input is mutated."""
        if self._layout() != other._layout():
            raise ValueError(
                f"cannot merge histograms with different layouts: "
                f"{self._layout()} vs {other._layout()}")
        out = HistogramCounter(self.lo, self.hi, self.subbuckets)
        out.counts = [a + b for a, b in zip(self.counts, other.counts)]
        out.count = self.count + other.count
        out.sum = self.sum + other.sum
        out.vmin = min(self.vmin, other.vmin)
        out.vmax = max(self.vmax, other.vmax)
        return out

    def snapshot(self) -> Dict[str, Any]:
        """JSON-safe point-in-time state (min/max become None when
        empty — inf is not JSON).  When an exemplar reservoir is
        attached and holds captures, they embed under "exemplars" —
        that is how ``--metrics-out`` artifacts link a p99 cell to the
        offending rid."""
        snap = {
            "lo": self.lo, "hi": self.hi, "subbuckets": self.subbuckets,
            "count": self.count, "sum": self.sum,
            "min": self.vmin if self.count else None,
            "max": self.vmax if self.count else None,
            "counts": list(self.counts),
        }
        ex = self._ex
        if ex is not None and ex.captured:
            snap["exemplars"] = ex.exemplars()
        return snap

    @classmethod
    def from_snapshot(cls, snap: Dict[str, Any]) -> "HistogramCounter":
        h = cls(snap["lo"], snap["hi"], snap["subbuckets"])
        h.counts = [int(c) for c in snap["counts"]]
        h.count = int(snap["count"])
        h.sum = float(snap["sum"])
        if snap.get("min") is not None:
            h.vmin = float(snap["min"])
            h.vmax = float(snap["max"])
        elif h.count:
            # delta snapshots lose min/max: derive conservative bounds
            # from the occupied buckets so quantile clamping stays sane
            occupied = [i for i, c in enumerate(h.counts) if c]
            h.vmin = h.lo if occupied[0] == 0 else \
                h.lo * math.exp((occupied[0] - 1) * h._log_gamma)
            h.vmax = h.hi if occupied[-1] > h._nb else \
                h.bucket_upper(occupied[-1])
        return h

    def delta(self, prev: Dict[str, Any]) -> Dict[str, Any]:
        """Snapshot of what was recorded SINCE ``prev`` (an earlier
        :meth:`snapshot` of this histogram).  min/max are None — they
        are not recoverable for a window — so a histogram rebuilt via
        :meth:`from_snapshot` derives bounds from the bucket layout."""
        if (prev["lo"], prev["hi"], prev["subbuckets"]) != self._layout():
            raise ValueError("delta against a different bucket layout")
        return {
            "lo": self.lo, "hi": self.hi, "subbuckets": self.subbuckets,
            "count": self.count - int(prev["count"]),
            "sum": self.sum - float(prev["sum"]),
            "min": None, "max": None,
            "counts": [max(0, a - int(b))
                       for a, b in zip(self.counts, prev["counts"])],
        }

    # -- Counter interface --------------------------------------------

    def get_value(self, reset: bool = False) -> pc.CounterValue:
        v = self.mean()
        n = self.count
        if reset:
            self.counts = [0] * (self._nb + 2)
            self.count = 0
            self.sum = 0.0
            self.vmin = math.inf
            self.vmax = -math.inf
        return pc.CounterValue(v, time.time(), max(n, 1))


def latency_histograms() -> Dict[str, HistogramCounter]:
    """One fresh histogram per latency family (:data:`LATENCY_KEYS`) —
    the per-worker unit the routers keep and merge fleet-wide."""
    return {k: HistogramCounter() for k in LATENCY_KEYS}


def register_histogram(object_: str, counter: str,
                       hist: HistogramCounter, instance: str = "total",
                       locality: Optional[int] = None,
                       quantiles: Optional[Sequence[float]] = None
                       ) -> List[str]:
    """Register ``hist`` under the counter grammar plus one derived
    ``.../pNN`` CallbackCounter per configured quantile.  Returns every
    name registered (callers own unregistration, e.g. via the
    cache/counters refresh hook).  The derived counters close over the
    histogram only — they never keep its owner alive."""
    names: List[str] = []
    base = pc.counter_name(object_, counter, instance, locality)
    pc.register_counter(base, hist)
    names.append(base)
    for q in (configured_quantiles() if quantiles is None else quantiles):
        name = pc.counter_name(object_, f"{counter}/{quantile_label(q)}",
                               instance, locality)
        pc.register_counter(
            name, pc.CallbackCounter(lambda h=hist, q=q: h.quantile(q)))
        names.append(name)
    return names


# ---------------------------------------------------------------------------
# Per-request lifecycle timelines
# ---------------------------------------------------------------------------

class RequestTimeline:
    """Bounded rid-keyed event log.  ``event(rid, name, **attrs)``
    appends a monotonic-stamped event; when the table holds
    ``capacity`` rids the LEAST-RECENTLY-TOUCHED rid's whole timeline
    is dropped (drop-oldest by activity, like the Tracer ring — an
    in-flight request never loses its prefix to a retired one).
    Appends are GIL-cheap; no lock."""

    def __init__(self, capacity: Optional[int] = None) -> None:
        if capacity is None:
            capacity = _cfg().get_int("hpx.metrics.timeline_capacity",
                                      1024)
        self.capacity = max(1, int(capacity))
        self._rids: "OrderedDict[Any, List[Dict[str, Any]]]" = \
            OrderedDict()
        self.dropped = 0

    def event(self, rid: Any, name: str, t: Optional[float] = None,
              **attrs: Any) -> None:
        ev: Dict[str, Any] = {
            "name": name, "t": time.monotonic() if t is None else t}
        if attrs:
            ev["attrs"] = attrs
        lst = self._rids.get(rid)
        if lst is None:
            global _timeline_dropped
            while len(self._rids) >= self.capacity:
                self._rids.popitem(last=False)
                self.dropped += 1
                _timeline_dropped += 1
            lst = self._rids[rid] = []
        else:
            self._rids.move_to_end(rid)
        lst.append(ev)

    def events(self, rid: Any) -> List[Dict[str, Any]]:
        return list(self._rids.get(rid, ()))

    def __len__(self) -> int:
        return len(self._rids)

    def snapshot(self) -> Dict[Any, List[Dict[str, Any]]]:
        return {rid: list(evs) for rid, evs in self._rids.items()}


# process-wide LRU-eviction total across every RequestTimeline, read by
# the /runtime{...}/timeline/dropped-entries builtin (parallel to
# trace/dropped-spans) — per-instance counts stay on each timeline's
# ``dropped``.  GIL-atomic int bump, same discipline as Tracer.dropped.
_timeline_dropped = 0


def timeline_dropped_entries() -> int:
    return _timeline_dropped


def reset_timeline_dropped() -> None:
    global _timeline_dropped
    _timeline_dropped = 0


# ---------------------------------------------------------------------------
# Exposition
# ---------------------------------------------------------------------------

def _prom_name(path: pc.CounterPath) -> str:
    raw = f"hpx_{path.object}_{path.counter}"
    return "".join(ch if ch.isalnum() or ch == "_" else "_"
                   for ch in raw)


def _prom_escape(v: Any) -> str:
    """Label-value escaping shared by both exposition formats:
    backslash, double-quote, and newline must be escaped or a scraper
    mis-parses the row (both the v0.0.4 text format and OpenMetrics
    specify exactly these three)."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _prom_labels(path: pc.CounterPath) -> str:
    return (f'{{locality="{_prom_escape(path.locality)}",'
            f'instance="{_prom_escape(path.instance)}"}}')


# content types for the two exposition formats /varz negotiates between
PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
OPENMETRICS_CONTENT_TYPE = \
    "application/openmetrics-text; version=1.0.0; charset=utf-8"


def negotiate_exposition(accept: Optional[str]) -> Tuple[bool, str]:
    """Content-type negotiation for a scrape endpoint: an Accept
    header naming ``application/openmetrics-text`` selects OpenMetrics
    (exemplars + ``# EOF``); anything else gets the classic v0.0.4
    text format.  Returns ``(openmetrics, content_type)``."""
    if accept and "application/openmetrics-text" in accept:
        return True, OPENMETRICS_CONTENT_TYPE
    return False, PROM_CONTENT_TYPE


def _exemplar_suffix(e: Dict[str, Any]) -> str:
    """OpenMetrics exemplar clause appended to a ``_bucket`` row:
    ``# {rid="..."} value ts``."""
    rid = "" if e.get("rid") is None else e["rid"]
    return (f' # {{rid="{_prom_escape(rid)}"}} '
            f'{float(e["value"]):.9g} {float(e["ts"]):.3f}')


def render_prometheus(pattern: str = "*",
                      openmetrics: bool = False) -> str:
    """Text exposition of every registered counter matching
    ``pattern``.  HistogramCounters render as native histograms —
    cumulative ``_bucket{le=...}`` rows for each occupied bucket plus
    ``le="+Inf"``, ``_sum`` and ``_count``; scalar counters render as
    gauges.  Counter callbacks that raise are skipped (a half-dead
    worker must not take the scrape down with it).

    The default is the Prometheus v0.0.4 text format, byte-stable
    against earlier releases.  ``openmetrics=True`` switches to
    OpenMetrics 1.0: each bucket row carries its newest captured
    exemplar (``# {rid="..."} value ts``) and the payload terminates
    with ``# EOF``."""
    lines: List[str] = []
    seen_types: Dict[str, str] = {}
    for name, c in pc.registered_counters(pattern).items():
        try:
            path = pc.parse_counter_name(name)
            metric = _prom_name(path)
            labels = _prom_labels(path)
            if isinstance(c, HistogramCounter):
                if seen_types.setdefault(metric, "histogram") != \
                        "histogram":
                    continue
                ex_by_bucket: Dict[int, Dict[str, Any]] = {}
                if openmetrics and c._ex is not None:
                    ex_by_bucket = c._ex.newest_per_bucket()
                lines.append(f"# TYPE {metric} histogram")
                cum = 0
                for i, n in enumerate(c.counts):
                    if not n:
                        continue
                    cum += n
                    le = c.bucket_upper(i)
                    le_s = "+Inf" if math.isinf(le) else f"{le:.9g}"
                    ex = ex_by_bucket.get(i)
                    lines.append(
                        f'{metric}_bucket{{le="{le_s}",'
                        f'locality="{_prom_escape(path.locality)}",'
                        f'instance="{_prom_escape(path.instance)}"}} '
                        f'{cum}'
                        + (_exemplar_suffix(ex) if ex else ""))
                lines.append(
                    f'{metric}_bucket{{le="+Inf",'
                    f'locality="{_prom_escape(path.locality)}",'
                    f'instance="{_prom_escape(path.instance)}"}} '
                    f'{c.count}')
                lines.append(f"{metric}_sum{labels} {c.sum:.9g}")
                lines.append(f"{metric}_count{labels} {c.count}")
            else:
                if seen_types.setdefault(metric, "gauge") != "gauge":
                    continue
                v = c.get_value().value
                lines.append(f"# TYPE {metric} gauge")
                lines.append(f"{metric}{labels} {float(v):.9g}")
        except Exception:
            continue
    if openmetrics:
        lines.append("# EOF")
    return "\n".join(lines) + ("\n" if lines else "")


def registry_snapshot(pattern: str = "*") -> Dict[str, Dict[str, Any]]:
    """JSON-safe dump of the registry for ``--metrics-out`` artifacts:
    ``{"histograms": {name: snapshot}, "counters": {name: value}}``.
    Derived ``.../pNN`` counters land under "counters" like any other
    scalar; unreadable callbacks are skipped."""
    hists: Dict[str, Any] = {}
    scalars: Dict[str, float] = {}
    for name, c in pc.registered_counters(pattern).items():
        try:
            if isinstance(c, HistogramCounter):
                hists[name] = c.snapshot()
            else:
                scalars[name] = float(c.get_value().value)
        except Exception:
            continue
    return {"histograms": hists, "counters": scalars}
