"""Deterministic fault injection for the serving/runtime stack.

Reference analog: none in HPX proper — this is the chaos harness the
resiliency layer (`svc/resiliency`) is tested against, in the spirit of
HPX's own resiliency unit tests that throw from inside replayed tasks.
Production code calls :func:`check` at its fault DISPATCH SITES (the
decode/prefill/verify program dispatches in ``models/serving.py``,
``BlockAllocator.alloc``, the ``dist/actions`` send path); with no
injector installed that is one global read and a ``None`` compare —
the hot loop pays nothing.

An installed :class:`FaultInjector` decides *deterministically* whether
the Nth check of a site faults:

* an explicit **schedule** — ``{"decode": {3, 10}}`` faults the 3rd and
  10th decode checks, nothing else; the precision tool for tests;
* a seeded **rate** — every check draws from a per-site
  ``random.Random`` stream (streams are independent, so adding checks
  of one site never perturbs another's draws); same seed + same call
  order = same faults, which is what lets the chaos bench demand
  sha-identical output across a faulted and a fault-free run.

Faults are typed by site: ``alloc`` raises :class:`InjectedOOM` (a
``CacheOOM`` subclass — it walks the allocator's evict→retry→shed
ladder), ``locality`` and the ``disagg.*`` worker sites raise
:class:`LocalityLost` (a ``NetworkError`` —
`async_replay_distributed` retargets on it), everything else raises
plain :class:`InjectedFault`. All carry ``.site`` and ``.nth`` so
recovery policy can classify (e.g. serving disables speculation after
repeated ``verify`` faults).

The parcel sites (``parcel.drop``/``parcel.dup``/``parcel.delay``/
``net.partition``) are BEHAVIORAL: their fault is an action (lose,
duplicate or delay a wire message; tear a link) rather than an
exception, so their dispatch points call :func:`fires` — the same
deterministic decision (schedule nth membership, or a per-site seeded
stream draw) returned as a bool instead of raised. ``Runtime.
_send_to_locality`` / ``_handle_parcel`` consult them; idempotency
keys on the parcel layer make drop+resend and dup exactly-once.

Config (``hpx.fault.*``)::

    hpx.fault.enable     install_from_config() installs when truthy
    hpx.fault.seed       RNG seed for rate-based injection
    hpx.fault.rate       per-check fault probability
    hpx.fault.sites      csv of armed sites ("" = all)
    hpx.fault.max        total fault cap (0 = unlimited)
    hpx.fault.schedule   csv "site:nth" explicit schedule entries
"""

from __future__ import annotations

import random
from typing import Dict, Iterable, Mapping, Optional, Set, Tuple

from ..core.errors import (CacheOOM, Error, HpxError,
                           LocalityLost as _RealLocalityLost)
from ..synchronization import Mutex

__all__ = [
    "FaultInjector",
    "InjectedFault",
    "InjectedOOM",
    "LocalityLost",
    "SITES",
    "active",
    "check",
    "fires",
    "install",
    "install_from_config",
    "uninstall",
]

# the known dispatch sites, for docs/validation (unknown site names are
# still allowed — subsystems may grow new sites without touching this).
# "disagg.prefill"/"disagg.decode" are the per-ROLE worker-call sites
# (each counts its own stream, so a schedule can kill exactly one
# worker of each role); the parcel.* / net.partition sites are
# behavioral (fires(), not check()).
SITES = ("decode", "prefill", "verify", "alloc", "locality",
         "disagg.prefill", "disagg.decode",
         "parcel.drop", "parcel.dup", "parcel.delay", "net.partition")


class InjectedFault(HpxError):
    """A fault the injector raised at a dispatch site — the serving
    retry/restore ladder treats it as transient and recoverable."""

    def __init__(self, site: str, nth: int, message: str = ""):
        super().__init__(Error.internal_server_error,
                         message or f"injected fault at site "
                         f"{site!r} (check #{nth})",
                         "FaultInjector.check")
        self.site = site
        self.nth = nth


class InjectedOOM(CacheOOM, InjectedFault):
    """Injected pool exhaustion: isinstance of BOTH CacheOOM (so the
    allocator's callers run their normal OOM→evict→retry discipline)
    and InjectedFault (so fault accounting sees it)."""

    def __init__(self, site: str, nth: int):
        CacheOOM.__init__(
            self, f"injected KV-pool OOM (check #{nth})",
            "FaultInjector.check")
        self.site = site
        self.nth = nth


class LocalityLost(_RealLocalityLost, InjectedFault):
    """Simulated locality loss on the action send path — what a died
    decode/prefill worker looks like to `dist/actions` callers;
    `async_replay_distributed` retargets the next locality on it.
    Subclasses the REAL `core.errors.LocalityLost` the failure
    detector raises, so one except clause handles both worlds."""

    def __init__(self, site: str, nth: int, locality: int = -1):
        _RealLocalityLost.__init__(
            self, locality,
            f"injected locality loss toward locality "
            f"{locality} (check #{nth})", "FaultInjector.check")
        self.site = site
        self.nth = nth


def _raise_for(site: str, nth: int, **ctx) -> None:
    if site == "alloc":
        raise InjectedOOM(site, nth)
    if site == "locality" or site.startswith("disagg."):
        raise LocalityLost(site, nth, int(ctx.get("locality", -1)))
    raise InjectedFault(site, nth)


class FaultInjector:
    """Deterministic per-site fault source. Thread-safe: per-site
    check counters and RNG draws mutate under one Mutex (sites fire
    from the serving loop, the allocator, and action senders)."""

    def __init__(self, seed: int = 0, rate: float = 0.0,
                 sites: Optional[Iterable[str]] = None,
                 max_faults: int = 0,
                 schedule: Optional[Mapping[str, Iterable[int]]] = None,
                 ) -> None:
        if rate < 0.0 or rate > 1.0:
            raise ValueError(f"fault rate must be in [0, 1], got {rate}")
        self.seed = int(seed)
        self.rate = float(rate)
        self.sites: Optional[Set[str]] = (None if sites is None
                                          else {s for s in sites if s})
        self.max_faults = int(max_faults)
        self.schedule: Dict[str, Set[int]] = {
            site: {int(n) for n in nths}
            for site, nths in (schedule or {}).items()}
        self._rngs: Dict[str, random.Random] = {}
        self._checks: Dict[str, int] = {}
        self._injected: Dict[str, int] = {}
        self._lock = Mutex()

    # -- the decision -----------------------------------------------------

    def _armed(self, site: str) -> bool:
        return self.sites is None or site in self.sites

    def _decide(self, site: str) -> Tuple[bool, int]:
        """One counted dispatch through `site` → (fires, nth). Called
        under self._lock."""
        nth = self._checks.get(site, 0) + 1
        self._checks[site] = nth
        if not self._armed(site):
            return False, nth
        total = sum(self._injected.values())
        if self.max_faults and total >= self.max_faults:
            return False, nth
        fire = nth in self.schedule.get(site, ())
        if not fire and self.rate > 0.0:
            rng = self._rngs.get(site)
            if rng is None:
                # independent per-site streams: one site's check
                # count never perturbs another site's draws
                rng = random.Random(f"{self.seed}:{site}")
                self._rngs[site] = rng
            fire = rng.random() < self.rate
        if fire:
            self._injected[site] = self._injected.get(site, 0) + 1
        return fire, nth

    def check(self, site: str, **ctx) -> None:
        """Count one dispatch through `site`; raise its typed fault if
        the schedule/rate says this one dies."""
        with self._lock:
            fire, nth = self._decide(site)
        if fire:
            _raise_for(site, nth, **ctx)

    def fires(self, site: str, **ctx) -> bool:
        """`check` for BEHAVIORAL sites: same deterministic decision
        (same counters, same streams), returned instead of raised —
        the dispatch point acts the fault out (drop/duplicate/delay a
        parcel, tear a link) rather than unwinding."""
        with self._lock:
            fire, _nth = self._decide(site)
        return fire

    # -- observability ----------------------------------------------------

    def stats(self) -> Dict[str, Dict[str, int]]:
        """{site: {"checks": N, "injected": M}} for every site seen."""
        with self._lock:
            return {site: {"checks": n,
                           "injected": self._injected.get(site, 0)}
                    for site, n in sorted(self._checks.items())}

    @property
    def total_injected(self) -> int:
        with self._lock:
            return sum(self._injected.values())


# -- process-wide installation (one injector; None = everything passes) -----

_active: Optional[FaultInjector] = None


def install(injector: FaultInjector) -> FaultInjector:
    """Install `injector` as THE process-wide fault source (replacing
    any previous one) and return it."""
    global _active
    _active = injector
    return injector


def uninstall() -> Optional[FaultInjector]:
    """Remove the active injector (returns it); checks become no-ops."""
    global _active
    fi, _active = _active, None
    return fi


def active() -> Optional[FaultInjector]:
    return _active


def check(site: str, **ctx) -> None:
    """The dispatch-site hook: no-op unless an injector is installed."""
    fi = _active
    if fi is not None:
        fi.check(site, **ctx)


def fires(site: str, **ctx) -> bool:
    """Behavioral-site hook: False unless an injector is installed and
    schedules this dispatch."""
    fi = _active
    if fi is not None:
        return fi.fires(site, **ctx)
    return False


def install_from_config() -> Optional[FaultInjector]:
    """Build + install an injector from ``hpx.fault.*`` when
    ``hpx.fault.enable`` is truthy; returns it (or None when fault
    injection is off). Operator entry point — tests and the chaos
    bench construct FaultInjector directly for precise schedules."""
    from ..core.config import runtime_config
    rc = runtime_config()
    if not rc.get_bool("hpx.fault.enable", False):
        return None
    sites_csv = (rc.get("hpx.fault.sites") or "").strip()
    sites = ([s.strip() for s in sites_csv.split(",") if s.strip()]
             or None)
    schedule: Dict[str, Set[int]] = {}
    for part in (rc.get("hpx.fault.schedule") or "").split(","):
        part = part.strip()
        if not part:
            continue
        site, _, nth = part.partition(":")
        if not nth:
            raise ValueError(
                f"hpx.fault.schedule entries are site:nth, got {part!r}")
        schedule.setdefault(site.strip(), set()).add(int(nth))
    return install(FaultInjector(
        seed=rc.get_int("hpx.fault.seed", 0),
        rate=rc.get_float("hpx.fault.rate", 0.0),
        sites=sites,
        max_faults=rc.get_int("hpx.fault.max", 0),
        schedule=schedule))
