"""Performance counters — the reference's primary observability surface.

Reference analog: libs/full/performance_counters (SURVEY.md §2.5, §5.1):
hierarchical named counters `/object{locality#N/instance}/counter`, a
registry with discovery, query (with optional reset), remote query via
actions, and `--hpx:print-counter[-interval]` style printing.

TPU-first feeds: the host task pools (executed/stolen/pending), the
TpuExecutor (dispatches, XLA compilations = jit-cache misses), the
parcel layer (count/bytes sent+received), and runtime uptime. Device-side
metrics (HBM in use, per-program stats) come from jax's memory_stats on
the counter's locality.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import re
import threading
from collections import deque

from ..synchronization import Mutex
import time
from typing import Any, Callable, Dict, List, Optional

from ..core.errors import Error, HpxError

# ---------------------------------------------------------------------------
# Counter naming: /objectname{locality#N/instance}/countername
# ---------------------------------------------------------------------------

_NAME_RE = re.compile(
    r"^/(?P<object>[^{/]+)\{locality#(?P<locality>\d+|\*)/"
    r"(?P<instance>[^}]+)\}/(?P<counter>.+)$")


@dataclasses.dataclass(frozen=True)
class CounterPath:
    object: str
    locality: str          # digits or "*"
    instance: str
    counter: str

    def format(self) -> str:
        return (f"/{self.object}{{locality#{self.locality}/"
                f"{self.instance}}}/{self.counter}")


def parse_counter_name(name: str) -> CounterPath:
    m = _NAME_RE.match(name)
    if not m:
        raise HpxError(Error.bad_parameter,
                       f"malformed counter name: {name!r} (expected "
                       "/object{locality#N/instance}/counter)")
    return CounterPath(m.group("object"), m.group("locality"),
                       m.group("instance"), m.group("counter"))


def counter_name(object: str, counter: str, instance: str = "total",
                 locality: Optional[int] = None) -> str:
    if locality is None:
        from ..dist.runtime import find_here
        locality = find_here()
    return f"/{object}{{locality#{locality}/{instance}}}/{counter}"


# ---------------------------------------------------------------------------
# Counter kinds
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class CounterValue:
    value: float
    timestamp: float
    count: int = 1         # samples aggregated (1 for raw counters)


class Counter:
    def get_value(self, reset: bool = False) -> CounterValue:
        raise NotImplementedError


class GaugeCounter(Counter):
    """Manually incremented/set value (monotonic or gauge)."""

    def __init__(self, initial: float = 0.0) -> None:
        self._v = initial
        self._lock = Mutex()

    def add(self, delta: float = 1.0) -> None:
        with self._lock:
            self._v += delta

    def set(self, value: float) -> None:
        with self._lock:
            self._v = value

    def get_value(self, reset: bool = False) -> CounterValue:
        with self._lock:
            v = self._v
            if reset:
                self._v = 0.0
        return CounterValue(v, time.time())


class CallbackCounter(Counter):
    """Value pulled from a callback at query time (most built-ins)."""

    def __init__(self, fn: Callable[[], float],
                 reset_fn: Optional[Callable[[], None]] = None) -> None:
        self._fn = fn
        self._reset = reset_fn
        self._base = 0.0   # software reset: subtract snapshot

    def get_value(self, reset: bool = False) -> CounterValue:
        raw = float(self._fn())
        v = raw - self._base
        if reset:
            if self._reset is not None:
                self._reset()
                self._base = 0.0
            else:
                self._base = raw
        return CounterValue(v, time.time())


_MODULE_T0 = time.monotonic()  # process-lifetime anchor for uptime


class ElapsedTimeCounter(Counter):
    """Registration can be lazy (first remote query), so anchor to module
    import time by default — otherwise a register-then-read in the same
    clock quantum reports uptime == 0."""

    def __init__(self, t0: Optional[float] = None) -> None:
        self._t0 = _MODULE_T0 if t0 is None else t0

    def get_value(self, reset: bool = False) -> CounterValue:
        now = time.monotonic()
        v = now - self._t0
        if reset:
            self._t0 = now
        return CounterValue(v, time.time())


class RateCounter(Counter):
    """Windowed events/sec: `mark(n)` records n events now; the value
    is the event total landed inside the trailing `window_s` seconds
    divided by the window. Serving uses it for tokens/sec — a
    cumulative GaugeCounter can't answer "how fast NOW", and an
    AverageCounter's mean-of-samples isn't a rate at all.

    `get_value()` is a step function of the event times: a burst holds
    its full rate until the instant its events age past the window,
    then cliffs to 0. Fine for dashboards; wrong for a CONTROLLER —
    across an idle gap the tuner would read ghost throughput and tune
    against work that stopped seconds ago. `rate()` is the
    controller-facing read: the same pruned total, decayed linearly
    against the wall-clock gap since the NEWEST event, so an idle
    window drains smoothly to 0 instead of holding stale."""

    def __init__(self, window_s: float = 10.0) -> None:
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        self._window = float(window_s)
        self._events: "deque" = deque()     # (monotonic time, n)
        self._lock = Mutex()

    def _prune(self, now: float) -> None:
        cutoff = now - self._window
        while self._events and self._events[0][0] < cutoff:
            self._events.popleft()

    def mark(self, n: float = 1.0) -> None:
        now = time.monotonic()
        with self._lock:
            self._events.append((now, float(n)))
            self._prune(now)

    def get_value(self, reset: bool = False) -> CounterValue:
        now = time.monotonic()
        with self._lock:
            self._prune(now)
            total = sum(n for _, n in self._events)
            count = len(self._events)
            if reset:
                self._events.clear()
        return CounterValue(total / self._window, time.time(),
                            max(count, 1))

    def rate(self) -> float:
        """Wall-clock-decayed events/sec for controllers: the pruned
        in-window total over the window, scaled by how recently the
        NEWEST event landed — full weight at gap 0, linearly down to 0
        after one idle window. Marking anything restores full weight,
        so an active stream reads identically to get_value()."""
        now = time.monotonic()
        with self._lock:
            self._prune(now)
            if not self._events:
                return 0.0
            total = sum(n for _, n in self._events)
            gap = now - self._events[-1][0]
        decay = max(0.0, 1.0 - gap / self._window)
        return (total / self._window) * decay


class AverageCounter(Counter):
    """Accumulates samples; value = mean since last reset."""

    def __init__(self) -> None:
        self._sum = 0.0
        self._n = 0
        self._lock = Mutex()

    def sample(self, value: float) -> None:
        with self._lock:
            self._sum += value
            self._n += 1

    def get_value(self, reset: bool = False) -> CounterValue:
        with self._lock:
            v = self._sum / self._n if self._n else 0.0
            n = self._n
            if reset:
                self._sum, self._n = 0.0, 0
        return CounterValue(v, time.time(), max(n, 1))


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

# hpxlint: disable-next=HPX004 — defensively reentrant: counter
# callbacks and refresh hooks may register/query while discovery holds
# the lock; a non-reentrant Mutex would self-deadlock
_registry_lock = threading.RLock()
_registry: Dict[str, Counter] = {}
_refresh_hooks: List[Callable[[], None]] = []


def register_counter(name: str, counter: Counter) -> Counter:
    parse_counter_name(name)   # validate
    with _registry_lock:
        _registry[name] = counter
    return counter


def unregister_counter(name: str) -> None:
    with _registry_lock:
        _registry.pop(name, None)


def register_refresh_hook(fn: Callable[[], None]) -> None:
    """Hook run before discovery/query to (re)register counters for
    dynamically created objects (pools, executors, parcel layer)."""
    with _registry_lock:
        if fn not in _refresh_hooks:
            _refresh_hooks.append(fn)


def _refresh() -> None:
    with _registry_lock:
        hooks = list(_refresh_hooks)
    for fn in hooks:
        fn()


def discover_counters(pattern: str = "*") -> List[str]:
    """All registered counter names matching the fnmatch pattern.
    `locality#*` in the pattern matches any locality."""
    _refresh()
    with _registry_lock:
        names = list(_registry)
    return sorted(n for n in names if fnmatch.fnmatchcase(n, pattern))


def registered_counters(pattern: str = "*") -> Dict[str, Counter]:
    """The Counter OBJECTS behind :func:`discover_counters` — exposition
    layers (svc/metrics render_prometheus) need the instances, not just
    query values, to tell histograms from scalars."""
    _refresh()
    with _registry_lock:
        snap = dict(_registry)
    return {n: snap[n] for n in sorted(snap)
            if fnmatch.fnmatchcase(n, pattern)}


def query_counter(name: str, reset: bool = False,
                  _do_refresh: bool = True) -> CounterValue:
    """Query one counter. A name addressed to another locality routes
    there as an action (remote counter query, as in the reference)."""
    path = parse_counter_name(name)
    from ..dist.runtime import find_here
    if path.locality != "*" and int(path.locality) != find_here():
        from ..dist.actions import async_action
        v, ts, n = async_action(_query_action, int(path.locality),
                                name, reset).get()
        return CounterValue(v, ts, n)
    if _do_refresh:
        _refresh()
    with _registry_lock:
        c = _registry.get(name)
    if c is None:
        raise HpxError(Error.bad_parameter, f"no such counter: {name}")
    return c.get_value(reset)


def query_counter_async(name: str, reset: bool = False):
    """query_counter returning a Future — remote queries dispatch
    without blocking, so callers can fan out over localities (the
    binpacked placement policy queries every candidate concurrently)."""
    from ..futures.future import make_ready_future
    path = parse_counter_name(name)
    from ..dist.runtime import find_here
    if path.locality != "*" and int(path.locality) != find_here():
        from ..dist.actions import async_action
        return async_action(_query_action, int(path.locality),
                            name, reset).then(
            lambda f: CounterValue(*f.get()))
    return make_ready_future(query_counter(name, reset))


def query_counters(pattern: str = "*", reset: bool = False
                   ) -> Dict[str, CounterValue]:
    # discover_counters already ran the refresh hooks once for this call
    return {n: query_counter(n, reset, _do_refresh=False)
            for n in discover_counters(pattern)}


def print_counters(pattern: str = "*", file=None, reset: bool = False) -> None:
    """--hpx:print-counter analog: one aligned line per counter."""
    import sys
    out = file or sys.stdout
    for name, cv in query_counters(pattern, reset).items():
        print(f"{name},{cv.count},{cv.timestamp:.6f},{cv.value:g}", file=out)


def start_counter_printing(interval_s: float, pattern: str = "*",
                           file=None) -> Callable[[], None]:
    """--hpx:print-counter-interval analog; returns a stop() function."""
    stop = threading.Event()

    def loop() -> None:
        while not stop.wait(interval_s):
            print_counters(pattern, file)

    t = threading.Thread(target=loop, daemon=True,
                         name="hpx-counter-printer")
    t.start()

    def stopper() -> None:
        stop.set()
        t.join(timeout=2.0)

    return stopper


# remote query action (registered lazily to avoid import cycles)
def _query_action_impl(name: str, reset: bool):
    cv = query_counter(name, reset)
    return (cv.value, cv.timestamp, cv.count)


from ..dist.actions import plain_action as _plain_action  # noqa: E402

_query_action = _plain_action(name="perf_counters.query")(_query_action_impl)


# ---------------------------------------------------------------------------
# Built-in counters
# ---------------------------------------------------------------------------

def _register_builtins() -> None:
    from ..dist.runtime import find_here
    loc = find_here()

    def put(object: str, counter: str, c: Counter, instance: str = "total"):
        name = counter_name(object, counter, instance, loc)
        with _registry_lock:
            if name not in _registry:
                _registry[name] = c

    # host task pool (scheduler counters). Resolve the CURRENT pool
    # inside each callback: binding the instance at registration left
    # the counters reading a dead pool forever after
    # reset_default_pool() (observed as a full-suite-order flake). Read
    # the module slot rather than calling default_pool() — a counter
    # poll must OBSERVE, never lazily resurrect a pool that was shut
    # down (same discipline as the native-pool counters below).
    def _dpool_stat(key):
        from ..runtime import threadpool as _tp
        p = _tp._default_pool
        return 0.0 if p is None else float(p.stats().get(key, 0))

    def _dpool_idle_rate():
        from ..runtime import threadpool as _tp
        p = _tp._default_pool
        if p is None:
            return 0.0
        st = p.stats()
        return float(st.get("idle", 0)) / max(1, st.get("threads", 1))

    put("threads", "count/cumulative",
        CallbackCounter(lambda: _dpool_stat("executed")), "pool#default")
    put("threads", "count/stolen",
        CallbackCounter(lambda: _dpool_stat("stolen")), "pool#default")
    put("threads", "queue/length",
        CallbackCounter(lambda: _dpool_stat("pending")), "pool#default")
    # HPX_WITH_THREAD_IDLE_RATES analog: parked workers / total, 0..1
    put("threads", "idle-rate",
        CallbackCounter(_dpool_idle_rate), "pool#default")

    # io_service helper pools (io/timer/parcel + user pools) — queue
    # length per named pool, like the reference's io_service counters.
    # Discovery happens at registration/refresh time (pools created
    # later appear on the next refresh hook run); the callback itself
    # reads through the locked accessor so it can race
    # shutdown_io_pools() safely.
    from ..runtime.io_service import io_pool_names, io_pool_pending
    for pname in io_pool_names():
        put("io", "queue/length",
            CallbackCounter(
                lambda p=pname: float(io_pool_pending(p))),
            f"pool#{pname}")

    # native C++ pools (exec/_make_pool-created NativePool instances):
    # cumulative executed/stolen from the scheduler's atomics, total
    # pending, and PER-WORKER queue depths. Discovery at refresh time
    # (pools created later appear on the next refresh hook run), but
    # callbacks resolve the pool BY NAME at every read — a recreated
    # same-name pool is picked up, a shut-down one reads 0, and no
    # instance is kept alive by observability (the io-pool pattern).
    try:
        from ..native.loader import (live_native_pools,
                                     native_pool_queue_len,
                                     native_pool_stat)
        pools = live_native_pools()
    except Exception:  # noqa: BLE001 — native runtime optional
        pools = []

    for np_ in pools:
        inst = f"pool#{np_.name}"
        nm = np_.name
        put("threads", "count/cumulative", CallbackCounter(
            lambda n=nm: native_pool_stat(n, "executed")), inst)
        put("threads", "count/stolen", CallbackCounter(
            lambda n=nm: native_pool_stat(n, "stolen")), inst)
        put("threads", "queue/length", CallbackCounter(
            lambda n=nm: native_pool_stat(n, "pending")), inst)
        put("threads", "idle-rate", CallbackCounter(
            lambda n=nm: native_pool_stat(n, "idle")
            / max(1.0, native_pool_stat(n, "threads"))), inst)
        for w in range(np_.num_threads):
            put("threads", "queue/length", CallbackCounter(
                lambda n=nm, w=w: float(native_pool_queue_len(n, w))),
                f"{inst}/worker-thread#{w}")

    # runtime uptime
    name = counter_name("runtime", "uptime", "total", loc)
    with _registry_lock:
        if name not in _registry:
            _registry[name] = ElapsedTimeCounter()

    # device executor
    from ..exec.tpu import TpuExecutor
    put("tpu", "count/dispatches",
        CallbackCounter(lambda: TpuExecutor.dispatch_count), "executor")
    put("tpu", "count/compilations",
        CallbackCounter(lambda: TpuExecutor.compile_count), "executor")

    # device memory (best-effort: not all backends report)
    def hbm_in_use() -> float:
        import jax
        try:
            st = jax.devices()[0].memory_stats()
            return float((st or {}).get("bytes_in_use", 0))
        except Exception:  # noqa: BLE001
            return 0.0
    put("tpu", "memory/bytes_in_use", CallbackCounter(hbm_in_use),
        "device#0")

    # host process memory (the reference's /runtime/memory/resident +
    # virtual counters); /proc/self/statm is linux-only — counters
    # read 0 elsewhere rather than failing discovery
    def _statm(field: int) -> Callable[[], float]:
        def read() -> float:
            try:
                import os as _os
                page = _os.sysconf("SC_PAGE_SIZE")
                with open("/proc/self/statm") as f:
                    return float(f.read().split()[field]) * page
            except (OSError, IndexError, ValueError, AttributeError):
                return 0.0
        return read
    put("runtime", "memory/virtual", CallbackCounter(_statm(0)))
    put("runtime", "memory/resident", CallbackCounter(_statm(1)))

    # observer health: external-timer / task-observer callbacks whose
    # exceptions were swallowed (svc/profiling) — nonzero means a
    # profiling hook is broken and silently dropping data
    from . import profiling as _prof
    put("runtime", "count/dropped-observer-callbacks",
        CallbackCounter(lambda: float(_prof.dropped_callbacks()),
                        reset_fn=_prof.reset_dropped_callbacks))

    # tracer-ring health: spans lost to the drop-oldest ring of the
    # ACTIVE process tracer (0 when tracing is off).  Nonzero means the
    # ring is undersized for the workload — raise hpx.trace.buffer_events
    # or narrow hpx.trace.counters.
    from . import tracing as _tracing

    def _dropped_spans() -> float:
        tr = _tracing.active_tracer()
        return float(tr.dropped) if tr is not None else 0.0
    put("runtime", "trace/dropped-spans",
        CallbackCounter(_dropped_spans))

    # timeline health: whole per-rid timelines LRU-evicted across every
    # RequestTimeline in the process (svc/metrics module aggregate —
    # parallel to trace/dropped-spans).  Nonzero means post-mortems for
    # those rids are gone — raise hpx.metrics.timeline_capacity.
    # Import lazily: metrics imports this module at its top level.
    def _timeline_dropped() -> float:
        from . import metrics as _metrics
        return float(_metrics.timeline_dropped_entries())

    def _timeline_dropped_reset() -> None:
        from . import metrics as _metrics
        _metrics.reset_timeline_dropped()
    put("runtime", "timeline/dropped-entries",
        CallbackCounter(_timeline_dropped,
                        reset_fn=_timeline_dropped_reset))

    # parcel layer (only once the distributed runtime is up). Read the
    # CURRENT runtime at query time: closing over the runtime object
    # alive at first registration would report frozen values (and pin a
    # dead Runtime) after a finalize()+init() cycle.
    from ..dist import runtime as rt
    if rt._runtime is not None:
        def _rt_attr(attr: str) -> Callable[[], float]:
            def read() -> float:
                r = rt._runtime
                return float(getattr(r, attr)) if r is not None else 0.0
            return read
        put("parcels", "count/sent",
            CallbackCounter(_rt_attr("parcels_sent")))
        put("parcels", "count/received",
            CallbackCounter(_rt_attr("parcels_received")))
        put("data", "count/sent",
            CallbackCounter(_rt_attr("bytes_sent")))
        put("data", "count/received",
            CallbackCounter(_rt_attr("bytes_received")))


register_refresh_hook(_register_builtins)
