"""Tail-bucket exemplar reservoirs for the SLO latency histograms.

A histogram can name a p99; it cannot name the *request* that caused
it.  An :class:`ExemplarReservoir` rides a
:class:`~hpx_tpu.svc.metrics.HistogramCounter` and, whenever a
``record()`` lands in a top-quantile bucket, captures an exemplar —
``(rid, value, wall_ts, trace-span ref)`` — so a p99 cell in a
serving_bench artifact or a ``/varz`` scrape links straight to the
offending request's ``RequestTimeline`` entry and Perfetto trace row.

Design constraints, in order:

* **Zero overhead when off.**  The histogram's ``_ex`` attribute is
  ``None`` unless :func:`attach` ran; the record fast path pays one
  attribute load + is-None test (the same discipline as
  ``tracing.active_tracer()``).
* **No O(buckets) work on the record path.**  "Top-quantile bucket"
  needs a threshold bucket index, which needs a cumulative scan — the
  exact cost hpxlint HPX023 bans from hot paths.  The reservoir caches
  the threshold and recomputes it every ``refresh`` offers, so the
  scan is amortized to ``O(buckets / refresh)`` per sample.
* **Deterministic replacement.**  Per-bucket ring: the n-th exemplar
  offered to a bucket lands in slot ``n % per_bucket``.  Same record
  sequence in, same exemplars out — no RNG, replayable in tests.

Knobs (``hpx.obs.*``): ``exemplars`` master switch,
``exemplars_per_bucket``, ``exemplar_quantile``, ``exemplar_refresh``.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from . import tracing

__all__ = [
    "ExemplarReservoir",
    "attach",
    "attach_from_config",
    "enabled",
]


def _cfg():
    from ..core.config import runtime_config
    return runtime_config()


def enabled() -> bool:
    """The ``hpx.obs.exemplars`` master switch."""
    return _cfg().get_bool("hpx.obs.exemplars", False)


class ExemplarReservoir:
    """Bounded per-bucket exemplar store for one histogram.

    ``offer(idx, value, rid)`` is called by the owning histogram's
    ``record()`` with the already-computed bucket index; it captures
    only when ``idx`` is at/above the cached top-quantile threshold
    bucket.  The threshold is recomputed from the histogram's bucket
    counts every ``refresh`` offers (cumulative scan, amortized)."""

    __slots__ = ("hist", "per_bucket", "quantile", "refresh",
                 "offered", "captured", "_thr", "_slots", "_seq")

    def __init__(self, hist: Any, per_bucket: int = 4,
                 quantile: float = 0.95, refresh: int = 64) -> None:
        self.hist = hist
        self.per_bucket = max(1, int(per_bucket))
        self.quantile = min(max(float(quantile), 0.0), 1.0)
        self.refresh = max(1, int(refresh))
        self.offered = 0
        self.captured = 0
        self._thr = 0                 # bucket index; 0 = capture all
        # bucket idx -> (ring of exemplar dicts, offers-to-bucket)
        self._slots: Dict[int, List[Optional[Dict[str, Any]]]] = {}
        self._seq: Dict[int, int] = {}

    # -- threshold ----------------------------------------------------

    def _recompute_threshold(self) -> None:
        """Smallest bucket index whose cumulative count reaches the
        configured quantile — records below it are not tail samples
        and are not captured."""
        h = self.hist
        total = h.count
        if not total:
            self._thr = 0
            return
        target = max(1, int(self.quantile * total))
        cum = 0
        for i, c in enumerate(h.counts):
            cum += c
            if cum >= target:
                self._thr = i
                return
        self._thr = len(h.counts) - 1

    # -- capture ------------------------------------------------------

    def offer(self, idx: int, value: float, rid: Any) -> None:
        """Called from ``HistogramCounter.record`` AFTER the counts
        update, with the sample's bucket index.  GIL-cheap: int
        compares plus a dict/list store when the sample is tail."""
        self.offered += 1
        if self._thr == 0 or (self.offered - 1) % self.refresh == 0:
            self._recompute_threshold()
        if idx < self._thr:
            return
        ring = self._slots.get(idx)
        if ring is None:
            ring = self._slots[idx] = [None] * self.per_bucket
            self._seq[idx] = 0
        n = self._seq[idx]
        self._seq[idx] = n + 1
        ring[n % self.per_bucket] = {
            "rid": rid,
            "value": float(value),
            "ts": time.time(),
            "span": tracing.current_span_id(),
            "bucket": idx,
        }
        self.captured += 1

    # -- reading ------------------------------------------------------

    def exemplars(self) -> List[Dict[str, Any]]:
        """Captured exemplars, bucket-ordered then capture-ordered —
        JSON-safe, embedded verbatim in snapshots and ``--metrics-out``
        artifacts."""
        out: List[Dict[str, Any]] = []
        for idx in sorted(self._slots):
            ring, n = self._slots[idx], self._seq[idx]
            live = min(n, self.per_bucket)
            start = n % self.per_bucket if n > self.per_bucket else 0
            for k in range(live):
                e = ring[(start + k) % self.per_bucket]
                if e is not None:
                    out.append(e)
        return out

    def newest_per_bucket(self) -> Dict[int, Dict[str, Any]]:
        """The most recent exemplar in each occupied bucket — the one
        a ``_bucket`` exposition row annotates."""
        out: Dict[int, Dict[str, Any]] = {}
        for idx in sorted(self._slots):
            n = self._seq[idx]
            if n:
                e = self._slots[idx][(n - 1) % self.per_bucket]
                if e is not None:
                    out[idx] = e
        return out


def attach(hist: Any, per_bucket: int = 4, quantile: float = 0.95,
           refresh: int = 64) -> ExemplarReservoir:
    """Attach a fresh reservoir to ``hist`` (replacing any prior one)
    and return it."""
    ex = ExemplarReservoir(hist, per_bucket=per_bucket,
                           quantile=quantile, refresh=refresh)
    hist._ex = ex
    return ex


def attach_from_config(hists: Any) -> List[ExemplarReservoir]:
    """Attach reservoirs (knob-configured) to every histogram in
    ``hists`` (a dict of name -> HistogramCounter, or a single
    histogram) when ``hpx.obs.exemplars`` is on; no-op list when off —
    callers need no gate of their own."""
    if not enabled():
        return []
    cfg = _cfg()
    per_bucket = cfg.get_int("hpx.obs.exemplars_per_bucket", 4)
    quantile = cfg.get_float("hpx.obs.exemplar_quantile", 0.95)
    refresh = cfg.get_int("hpx.obs.exemplar_refresh", 64)
    targets = hists.values() if hasattr(hists, "values") else [hists]
    return [attach(h, per_bucket=per_bucket, quantile=quantile,
                   refresh=refresh) for h in targets]
