"""Closed-loop adaptive executor: online tuning of the serving knobs.

Reference analog: the adaptive HPX executor of "A New Execution Model
and Executor for Adaptively Optimizing the Performance of Parallel
Algorithms Using HPX" — measure the live workload, move one execution
parameter a bounded step, keep the move only if the measured objective
improved. Here the "execution parameters" are the declared-tunable
serving knobs (``config_schema.tunable_keys()``: prefill chunk, async
depth, spec-k ceiling, checkpoint cadence, radix HBM budget, disagg
queue bound) and the measurement is the live signal plane: the decayed
``RateCounter.rate()`` tokens/s, the windowed decode-stall p99 from the
SLO histograms, the admission queue depth, and progprof's measured
compile seconds.

Control law — deterministic coordinate descent with probe/revert:

* The host server calls :meth:`AdaptiveTuner.maybe_tick` once per
  FLUSH (the one safe host boundary: no step is in flight, knob writes
  cannot tear a dispatched program). Every ``hpx.tune.interval_ticks``
  flushes the tuner samples the signals and runs one evaluation.
* In the MEASURE phase it banks the objective, picks the next eligible
  knob round-robin (sorted names, rotated by ``hpx.tune.seed``), and
  applies ONE bounded step in that knob's current direction (a probe).
* In the PROBE phase (the next evaluation) it compares objectives:
  the move is kept only when the relative improvement clears the
  ``hpx.tune.hysteresis_pct`` band — plus, for a knob declared
  ``compiles=True``, the measured compile seconds charged against the
  ``hpx.tune.compile_amortize_s`` horizon. Otherwise the knob reverts,
  flips direction, and cools down ``hpx.tune.cooldown_ticks``
  evaluations.

Every decision is a pure function of the signal-sample sequence — no
wall clock, no RNG draws — so a recorded history replays to identical
decisions (:func:`replay`); the flight recorder embeds each live
tuner's history + decisions per bundle (:func:`flight_snapshot`).

Output invariance: the tuner can only ever bind knobs present in
``config_schema.tunable_keys()`` — all proven output-invariant (they
change WHEN work is dispatched and what is recomputed, never which
tokens come out); the sha-identity tests pin this against the untuned
server. Compile-minting knobs are frozen while no program profiler is
active: an unmeasurable compile cost cannot be charged, so the move is
not taken (the compile-guard budgets stay intact).
"""

from __future__ import annotations

import dataclasses
import weakref
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from ..core.config_schema import Tunable
from ..synchronization import Mutex
from . import tracing

__all__ = [
    "TuneSignals",
    "KnobBinding",
    "TuneArbiter",
    "AdaptiveTuner",
    "server_tuner",
    "attach_arbiter",
    "replay",
    "flight_snapshot",
]

# knobs that spend a budget shared across workers (HBM, queue slots):
# under a router, only ONE worker may probe any of these at a time —
# two workers growing the radix budget together would double-spend the
# pool, and their probes would corrupt each other's measurements
SHARED_BUDGET_KNOBS = frozenset((
    "hpx.cache.radix_budget_blocks",
    "hpx.serving.disagg.max_queue",
))

# live tuners, observed weakly by the flight recorder — a dead server
# must not be pinned by its tuner's registration
_live: "weakref.WeakSet[AdaptiveTuner]" = weakref.WeakSet()


@dataclasses.dataclass(frozen=True)
class TuneSignals:
    """One evaluation's view of the signal plane. ``compile_s_total``
    is progprof's cumulative measured compile seconds (None = profiler
    off, which freezes every ``compiles=True`` knob)."""

    tok_rate: float            # decayed decode tokens/s (RateCounter.rate)
    stall_p99: float           # windowed decode-stall p99 seconds
    queue_depth: float         # admission queue depth
    compile_s_total: Optional[float] = None

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TuneSignals":
        return cls(tok_rate=float(d["tok_rate"]),
                   stall_p99=float(d["stall_p99"]),
                   queue_depth=float(d["queue_depth"]),
                   compile_s_total=(None if d.get("compile_s_total")
                                    is None
                                    else float(d["compile_s_total"])))


class KnobBinding:
    """One tunable knob bound to its live actuation point (a server
    attribute), with the declared bounds/step contract."""

    def __init__(self, name: str, spec: Tunable,
                 get: Callable[[], int],
                 set: Callable[[int], None]) -> None:
        self.name = name
        self.spec = spec
        self._get = get
        self._set = set

    def get(self) -> int:
        return int(self._get())

    def set(self, value: int) -> None:
        self._set(int(value))

    def step_from(self, value: int, direction: int) -> int:
        """The one bounded move from ``value`` in ``direction``
        (+1/-1), clamped into [lo, hi]; returns ``value`` itself when
        already pinned at that bound."""
        s = self.spec
        if s.geometric:
            nxt = value * s.step if direction > 0 else value // s.step
        else:
            nxt = value + (s.step if direction > 0 else -s.step)
        return max(s.lo, min(s.hi, nxt))


class TuneArbiter:
    """Router-level grant table for the shared-budget knobs: one
    holder at a time per knob name, so the prefill and decode sides
    of a disaggregated topology never fight over one budget. This
    lock nests inside nothing and takes nothing under it."""

    def __init__(self) -> None:
        self._lock = Mutex()
        self._holders: Dict[str, str] = {}   # knob name -> owner name

    def acquire(self, owner: str, knob: str) -> bool:
        with self._lock:
            cur = self._holders.get(knob)
            if cur is not None and cur != owner:
                return False
            self._holders[knob] = owner
            return True

    def release(self, owner: str, knob: str) -> None:
        with self._lock:
            if self._holders.get(knob) == owner:
                del self._holders[knob]


@dataclasses.dataclass
class _KnobState:
    """Per-knob controller state."""

    direction: int = 1         # next probe direction (+1/-1)
    cooldown: int = 0          # evaluations left to hold after revert
    pinned: int = 0            # consecutive at-bound probes skipped


class AdaptiveTuner:
    """The controller. Construct via :func:`server_tuner` for a live
    ``ContinuousServer``, or directly with synthetic knobs (the
    convergence tests do).

    Threading: single-threaded by contract — every mutation happens on
    the server flush thread via :meth:`maybe_tick`/:meth:`evaluate`
    (the one safe host boundary, see the module docstring), so none of
    the counters here take a lock. The only cross-thread surface is
    the :class:`TuneArbiter` grant table, which is mutex-guarded;
    hpxlint HPX019 checks the arbiter side and the real-tree analysis
    test pins this justification."""

    def __init__(self, knobs: List[KnobBinding], *,
                 name: str = "serving",
                 interval_ticks: int = 32,
                 w_tokens: float = 1.0,
                 w_stall: float = 100.0,
                 w_queue: float = 0.05,
                 hysteresis_pct: float = 5.0,
                 cooldown_ticks: int = 2,
                 compile_amortize_s: float = 30.0,
                 freeze: str = "",
                 seed: int = 0,
                 arbiter: Optional[TuneArbiter] = None,
                 history: int = 256) -> None:
        if interval_ticks < 1:
            raise ValueError(
                f"interval_ticks must be >= 1, got {interval_ticks}")
        self.name = name
        self.interval_ticks = int(interval_ticks)
        self.w_tokens = float(w_tokens)
        self.w_stall = float(w_stall)
        self.w_queue = float(w_queue)
        self.hysteresis_pct = float(hysteresis_pct)
        self.cooldown_ticks = max(0, int(cooldown_ticks))
        self.compile_amortize_s = max(1e-9, float(compile_amortize_s))
        self.seed = int(seed)
        self.arbiter = arbiter
        frozen = {f.strip() for f in str(freeze).split(",") if f.strip()}
        self._freeze_all = "*" in frozen
        self.frozen = frozenset(frozen - {"*"})
        # deterministic probe order: sorted names, rotated by seed
        self.knobs: Dict[str, KnobBinding] = {
            k.name: k for k in knobs}
        self._order = sorted(self.knobs)
        if self._order and self.seed:
            r = self.seed % len(self._order)
            self._order = self._order[r:] + self._order[:r]
        self._rr = 0                       # round-robin cursor
        self._kstate = {n: _KnobState() for n in self._order}
        # controller FSM
        self._phase = "measure"            # measure | probe
        self._probe: Optional[Dict[str, Any]] = None
        self._j_before = 0.0
        # accounting (the /serving{...}/tune/* counters read these)
        self.ticks = 0
        self.evals = 0
        self.probes = 0
        self.accepts = 0
        self.reverts = 0
        self.holds = 0
        # bounded decision + signal history (flight recorder / replay)
        self._decisions: deque = deque(maxlen=history)
        self._signals: deque = deque(maxlen=history)
        _live.add(self)

    # -- objective --------------------------------------------------------

    def objective(self, sig: TuneSignals) -> float:
        """Scalar J the controller maximizes: reward throughput,
        punish stall latency and queue backlog."""
        return (self.w_tokens * sig.tok_rate
                - self.w_stall * sig.stall_p99
                - self.w_queue * sig.queue_depth)

    # -- ticking ----------------------------------------------------------

    def maybe_tick(self, collect: Callable[[], TuneSignals],
                   hold: bool = False) -> Optional[Dict[str, Any]]:
        """Per-flush entry point: counts the tick and, every
        ``interval_ticks`` flushes, samples the signals and runs one
        evaluation. Signal collection only happens at evaluation
        boundaries — the between-boundary cost is one increment.
        ``hold=True`` (a firing SLO alert) blocks NEW probes this
        evaluation; an in-flight probe still settles."""
        self.ticks += 1
        if self.ticks % self.interval_ticks:
            return None
        return self.evaluate(collect(), hold=hold)

    # -- the FSM ----------------------------------------------------------

    def evaluate(self, sig: TuneSignals,
                 denied: Optional[Any] = None,
                 hold: bool = False) -> Optional[Dict[str, Any]]:
        """One controller evaluation against one signal sample. Pure
        in the sample sequence: same samples in, same decisions out.
        Arbiter grants and the alert-hold flag are the two external
        inputs — both are recorded INTO the stored signal sample so a
        replay (which passes them back) stays exact."""
        self.evals += 1
        rec = sig.as_dict()
        if hold:
            rec["alert_hold"] = True
        self._signals.append(rec)
        j = self.objective(sig)
        if self._phase == "probe":
            return self._settle_probe(sig, j)
        return self._start_probe(sig, j, denied, rec, hold)

    def _start_probe(self, sig: TuneSignals, j: float,
                     denied: Optional[Any],
                     rec: Dict[str, Any],
                     hold: bool = False) -> Optional[Dict[str, Any]]:
        self._j_before = j
        if hold:
            # alert-aware hold: while an SLO alert fires, the signal a
            # probe would be judged against is regressed traffic — a
            # knob move now tunes toward the incident, and the probe
            # itself can deepen it. Sit the evaluation out.
            self.holds += 1
            return self._log("hold", None, None, None, sig, j, j, 0.0)
        knob = self._next_knob(sig, denied, rec)
        if knob is None:
            self.holds += 1
            return self._log("hold", None, None, None, sig, j, j, 0.0)
        st = self._kstate[knob.name]
        old = knob.get()
        new = knob.step_from(old, st.direction)
        if new == old:
            # pinned at a bound: flip and try the other way next round
            st.direction = -st.direction
            self._release(knob.name)
            self.holds += 1
            return self._log("hold", knob.name, old, old, sig, j, j,
                             0.0)
        knob.set(new)
        self.probes += 1
        self._phase = "probe"
        self._probe = {
            "knob": knob.name, "old": old, "new": new,
            "compile_s0": sig.compile_s_total,
        }
        with tracing.span("serving.tune", "serving", action="probe",
                          tuner=self.name, knob=knob.name, old=old,
                          new=new):
            pass
        return self._log("probe", knob.name, old, new, sig, j, j, 0.0)

    def _settle_probe(self, sig: TuneSignals,
                      j: float) -> Optional[Dict[str, Any]]:
        assert self._probe is not None
        p, self._probe = self._probe, None
        self._phase = "measure"
        knob = self.knobs[p["knob"]]
        st = self._kstate[p["knob"]]
        charged = 0.0
        if knob.spec.compiles and p["compile_s0"] is not None \
                and sig.compile_s_total is not None:
            charged = max(0.0, sig.compile_s_total - p["compile_s0"])
        # a compile-minting move must clear hysteresis PLUS its
        # measured compile cost spread over the amortization horizon
        threshold = self.hysteresis_pct \
            + 100.0 * charged / self.compile_amortize_s
        base = max(abs(self._j_before), 1e-9)
        gain_pct = 100.0 * (j - self._j_before) / base
        if gain_pct >= threshold:
            self.accepts += 1
            action = "accept"
            # keep climbing the same direction next time this knob
            # comes around
        else:
            knob.set(p["old"])
            self.reverts += 1
            st.direction = -st.direction
            st.cooldown = self.cooldown_ticks
            action = "revert"
        self._release(p["knob"])
        with tracing.span("serving.tune", "serving", action=action,
                          tuner=self.name, knob=p["knob"],
                          old=p["old"], new=p["new"],
                          gain_pct=round(gain_pct, 3),
                          charged_s=round(charged, 6)):
            pass
        return self._log(action, p["knob"], p["old"], p["new"], sig,
                         self._j_before, j, charged)

    def _next_knob(self, sig: TuneSignals, denied: Optional[Any],
                   rec: Dict[str, Any]) -> Optional[KnobBinding]:
        """Round-robin over eligible knobs; ticks every knob's
        cooldown exactly once per evaluation. ``denied`` non-None
        means a replay: honor the recorded arbiter denials instead of
        consulting a live arbiter."""
        # a knob sits out cooldown_ticks FULL evaluations: snapshot
        # who is cooling before the decrement, skip on the snapshot
        cooling = {n for n, st in self._kstate.items()
                   if st.cooldown > 0}
        for st in self._kstate.values():
            if st.cooldown > 0:
                st.cooldown -= 1
        if self._freeze_all or not self._order:
            return None
        n = len(self._order)
        start = self._rr
        for i in range(n):
            idx = (start + i) % n
            name = self._order[idx]
            knob = self.knobs[name]
            if name in self.frozen:
                continue
            if name in cooling:
                continue
            if knob.spec.compiles and sig.compile_s_total is None:
                # no profiler: compile cost unmeasurable -> not movable
                continue
            if name in SHARED_BUDGET_KNOBS:
                if denied is not None:
                    if name in denied:
                        continue
                elif self.arbiter is not None \
                        and not self.arbiter.acquire(self.name, name):
                    rec.setdefault("denied", []).append(name)
                    continue
            self._rr = (idx + 1) % n
            return knob
        return None

    def _release(self, knob_name: str) -> None:
        if knob_name in SHARED_BUDGET_KNOBS and self.arbiter is not None:
            self.arbiter.release(self.name, knob_name)

    def _log(self, action: str, knob: Optional[str],
             old: Optional[int], new: Optional[int], sig: TuneSignals,
             j_before: float, j_after: float,
             charged: float) -> Dict[str, Any]:
        dec = {
            "eval": self.evals, "tick": self.ticks, "action": action,
            "knob": knob, "old": old, "new": new,
            "j_before": j_before, "j_after": j_after,
            "charged_compile_s": charged,
            "signals": sig.as_dict(),
        }
        self._decisions.append(dec)
        return dec

    # -- introspection ----------------------------------------------------

    def decisions(self) -> List[Dict[str, Any]]:
        return list(self._decisions)

    def signal_history(self) -> List[Dict[str, Any]]:
        return list(self._signals)

    def knob_values(self) -> Dict[str, int]:
        return {n: self.knobs[n].get() for n in self._order}

    def params(self) -> Dict[str, Any]:
        """The constructor parameters that shape decisions — enough,
        with the signal history and initial knob values, to replay."""
        return {
            "name": self.name,
            "interval_ticks": self.interval_ticks,
            "w_tokens": self.w_tokens, "w_stall": self.w_stall,
            "w_queue": self.w_queue,
            "hysteresis_pct": self.hysteresis_pct,
            "cooldown_ticks": self.cooldown_ticks,
            "compile_amortize_s": self.compile_amortize_s,
            "freeze": ",".join(
                sorted(self.frozen)
                + (["*"] if self._freeze_all else [])),
            "seed": self.seed,
        }

    def flight_state(self) -> Dict[str, Any]:
        """One tuner's slice of a flight bundle: what it moved, why,
        and the signal samples that drove it."""
        return {
            "params": self.params(),
            "knobs": {n: {"value": b.get(),
                          "spec": dataclasses.asdict(b.spec)}
                      for n, b in self.knobs.items()},
            "counters": {"ticks": self.ticks, "evals": self.evals,
                         "probes": self.probes,
                         "accepts": self.accepts,
                         "reverts": self.reverts, "holds": self.holds},
            "decisions": self.decisions(),
            "signals": self.signal_history(),
        }


# ---------------------------------------------------------------------------
# server glue
# ---------------------------------------------------------------------------

def from_config(knobs: List[KnobBinding], name: str = "serving",
                arbiter: Optional[TuneArbiter] = None
                ) -> "AdaptiveTuner":
    """Build a tuner from the ``hpx.tune.*`` knobs."""
    from ..core.config import runtime_config
    rc = runtime_config()
    return AdaptiveTuner(
        knobs, name=name,
        interval_ticks=max(1, rc.get_int("hpx.tune.interval_ticks",
                                         32)),
        w_tokens=rc.get_float("hpx.tune.w_tokens", 1.0),
        w_stall=rc.get_float("hpx.tune.w_stall", 100.0),
        w_queue=rc.get_float("hpx.tune.w_queue", 0.05),
        hysteresis_pct=rc.get_float("hpx.tune.hysteresis_pct", 5.0),
        cooldown_ticks=rc.get_int("hpx.tune.cooldown_ticks", 2),
        compile_amortize_s=rc.get_float("hpx.tune.compile_amortize_s",
                                        30.0),
        freeze=rc.get("hpx.tune.freeze", "") or "",
        seed=rc.get_int("hpx.tune.seed", 0),
        arbiter=arbiter)


def server_tuner(srv: Any, name: str = "serving",
                 arbiter: Optional[TuneArbiter] = None
                 ) -> "AdaptiveTuner":
    """Bind a ContinuousServer's live tunable attributes and build its
    tuner. Only knobs meaningful for THIS server's mode are bound
    (spec-k needs speculation on, the radix budget needs paged mode
    with a finite budget); bounds are capped to the server's baked
    ladders so a probe can never ask for an unreachable width."""
    from ..core import config_schema
    tk = config_schema.tunable_keys()
    knobs: List[KnobBinding] = []
    # learned Tunable ranges: a perfdb ladder hit at boot may carry
    # per-knob {lo, hi, step} re-derived by benchmarks/ladder_search
    # from the banked cost surface — the online tuner then walks the
    # learned range instead of the declared one.  geometric/compiles
    # semantics always come from the declaration (they are contracts,
    # not measurements), and the server's baked-ladder caps below
    # still apply last.
    learned_tun = (getattr(srv, "_learned_ladder", None)
                   or {}).get("tunables", {})

    def bind(key: str, getf: Callable[[], int],
             setf: Callable[[int], None],
             hi_cap: Optional[int] = None) -> None:
        entry = tk.get(key)
        if entry is None:       # not declared tunable: never bindable
            return
        spec = entry.tunable
        lt = learned_tun.get(key)
        if lt:
            spec = dataclasses.replace(
                spec,
                lo=max(spec.lo, int(lt.get("lo", spec.lo))),
                hi=min(spec.hi, int(lt.get("hi", spec.hi))),
                step=max(int(lt.get("step", spec.step)),
                         2 if spec.geometric else 1))
            if spec.lo > spec.hi:   # degenerate learned range
                spec = entry.tunable
        if hi_cap is not None:
            spec = dataclasses.replace(
                spec, hi=min(spec.hi, hi_cap),
                lo=min(spec.lo, hi_cap))
        knobs.append(KnobBinding(key, spec, getf, setf))

    ladder_max = srv.prefill_buckets[-1]
    bind("hpx.serving.prefill_chunk",
         lambda: srv.prefill_chunk,
         lambda v: setattr(srv, "prefill_chunk", v),
         hi_cap=ladder_max)
    if srv._async:
        bind("hpx.serving.max_async_steps",
             lambda: srv._max_async,
             lambda v: setattr(srv, "_max_async", v))
    bind("hpx.serving.ckpt_every",
         lambda: srv._ckpt_every,
         lambda v: setattr(srv, "_ckpt_every", v))
    if srv._spec:
        bind("hpx.serving.spec.k",
             lambda: srv._spec_k,
             lambda v: setattr(srv, "_spec_k", v),
             hi_cap=ladder_max - 1)
    if srv.paged and srv._radix.budget_blocks is not None:
        bind("hpx.cache.radix_budget_blocks",
             lambda: srv._radix.budget_blocks,
             lambda v: setattr(srv._radix, "budget_blocks", v))
    if getattr(srv.cfg, "n_experts", 0) > 0:
        # the percent knob ceilings at drop-free (cf = n_experts):
        # probing above it only pads the expert exchange wider
        bind("hpx.serving.moe.capacity_factor",
             lambda: srv._moe_capacity_pct,
             lambda v: setattr(srv, "_moe_capacity_pct", max(1, v)),
             hi_cap=srv.cfg.n_experts * 100)
    return from_config(knobs, name=name, arbiter=arbiter)


def attach_arbiter(handle: Any, arbiter: TuneArbiter,
                   name: str) -> None:
    """Join an in-proc worker's embedded tuner(s) to a router-level
    arbiter (and name them for the decision log). Remote workers live
    in their own process with their own budgets — nothing to share, so
    they are left alone."""
    worker = getattr(handle, "worker", None)
    if worker is None:
        return
    for attr in ("srv", "_eng"):
        srv = getattr(worker, attr, None)
        tuner = getattr(srv, "_tuner", None) if srv is not None else None
        if tuner is not None:
            tuner.arbiter = arbiter
            tuner.name = name


# ---------------------------------------------------------------------------
# replay (flight-recorder debugging)
# ---------------------------------------------------------------------------

def replay(state: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Re-run a recorded tuner history offline: rebuild the controller
    from a :meth:`AdaptiveTuner.flight_state` dict (as embedded in a
    flight bundle's ``tune`` section), bind its knobs to in-memory
    cells seeded from the recorded STARTING values, and feed the
    recorded signal samples back through. Decisions are a pure
    function of that history, so the replay reproduces the live run's
    decision log exactly — the debugging contract for "why did the
    tuner do that"."""
    params = dict(state["params"])
    params.pop("name", None)
    decisions = state.get("decisions", [])
    # recover each knob's value BEFORE the recorded window: walk the
    # decision log back from the current value
    values: Dict[str, int] = {n: int(k["value"])
                              for n, k in state["knobs"].items()}
    for dec in reversed(decisions):
        if dec["knob"] is None:
            continue
        if dec["action"] == "accept":
            values[dec["knob"]] = int(dec["old"])
        elif dec["action"] == "probe":
            # an unsettled probe left the new value applied
            values[dec["knob"]] = int(dec["old"])
    cells: Dict[str, int] = {}
    knobs: List[KnobBinding] = []
    for n, k in state["knobs"].items():
        cells[n] = values[n]
        knobs.append(KnobBinding(
            n, Tunable(**k["spec"]),
            (lambda n=n: cells[n]),
            (lambda v, n=n: cells.__setitem__(n, int(v)))))
    t = AdaptiveTuner(knobs, name=state["params"]["name"], **params)
    out: List[Dict[str, Any]] = []
    for s in state.get("signals", []):
        # live evaluations fire exactly when ticks % interval_ticks
        # == 0, so eval i happened at tick i*interval_ticks — advance
        # the counter the same way so the logged tick numbers match
        t.ticks += t.interval_ticks
        dec = t.evaluate(TuneSignals.from_dict(s),
                         denied=frozenset(s.get("denied", ())),
                         hold=bool(s.get("alert_hold")))
        if dec is not None:
            out.append(dec)
    return out


def flight_snapshot() -> Dict[str, Any]:
    """Every live tuner's flight_state, keyed by tuner name — the
    ``tune`` section :func:`svc.flight.build_bundle` embeds so a
    post-incident dump shows what the tuner did leading up to the
    fault. Empty dict when no tuner is live (zero-cost discipline:
    this only runs inside a bundle capture)."""
    out: Dict[str, Any] = {}
    for t in list(_live):
        key = t.name
        i = 1
        while key in out:       # two workers may share a default name
            i += 1
            key = f"{t.name}#{i}"
        out[key] = t.flight_state()
    return out
