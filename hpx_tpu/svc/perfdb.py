"""Persistent cross-run performance database (the offline tuner's memory).

Reference analog: the executor-parameter banking loop from "A New
Execution Model and Executor for Adaptively Optimizing the Performance
of Parallel Algorithms Using HPX" — measured (shape, parameter) costs
persist ACROSS runs so the next process starts from learned values
instead of compiled-in constants.  Here the banked surface is the
serving ladder economics progprof already measures: compile wall time
and per-call execute cost per program key, plus bench medians, keyed
on ``device kind x model shape x kv_dtype x kernel x mesh``.

Three producers feed the store:

* ``benchmarks/flash_tune.py --paged``      (block-size sweep medians)
* ``benchmarks/serving_bench`` waves        (tok/s + compile counts)
* the live progprof hook                    (``hpx.perfdb.record=1``)

and two consumers drain it:

* ``benchmarks/ladder_search.py`` — the offline search that re-derives
  the prefill bucket ladder, paged block-size table, spec-k bounds and
  AdaptiveTuner ``Tunable(lo,hi,step)`` ranges from the cost surface
  (``slo_gate.py`` arbitrates candidate artifacts, so compile-heavy
  exploration never touches the serving path), and
* ``ContinuousServer`` at boot — ``hpx.perfdb.use_learned_ladders=1``
  consults the store and, on a key hit with >= ``hpx.perfdb.
  min_samples`` samples, overrides the hand-picked defaults.  On a
  miss (or with the knob off, or an empty DB) the server resolves
  byte-identically to today's constants: this module is a pure perf
  layer, pinned by the identity tests in tests/test_perfdb.py.

Store layout (``PERFDB_SCHEMA`` = ``hpx_tpu.perfdb.v1``)::

    {"schema": "hpx_tpu.perfdb.v1",
     "observations": [ {id, key, metric, value, n, program?,
                        onchip, provenance, source, pid} ... ],
     "stats":    { "<key>::<metric>": {n, sum, sumsq, min, max,
                                       onchip_n} },
     "ladders":  { "<key>": {prefill_buckets, prefill_chunk,
                             block_size?, spec_k, tunables, samples,
                             onchip, provenance, rev} },
     "blocks":   { "hd<hd>x<kvd>": {block_size, samples, onchip,
                                    provenance, rev} }}

The observation log is APPEND-ONLY and merge-safe: each row's ``id``
is a content hash, ``save()`` re-reads the file and unions rows by id
before the atomic tmp+rename replace, so concurrent writers lose
nothing (two processes banking interleaved saves converge to the
union — pinned by tests).  ``compact()`` folds old rows into the
``stats`` summaries (sample counts + dispersion survive; raw rows
don't), which merge by addition.  Derived sections (``ladders``,
``blocks``) carry a monotonic ``rev``; merge keeps the higher rev,
tie-broken on content so the outcome is writer-order independent.

Provenance rides every row with the same stamps as bench.py:
``onchip``/``provenance`` default from the live backend (TPU ->
``on-chip``, anything else -> ``builder-session``), and
``ladder_search`` refuses to mint a "learned" ladder from
builder-session-only samples without ``--allow-session`` — the
ROADMAP tunnel backlog stays honest.

Counters: ``/perfdb{locality#N/total}/{keys,observations,hits,misses,
stale}`` — hits/misses count boot-time ladder lookups; ``stale``
counts key hits refused for insufficient samples or session-only
provenance.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
import os
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = [
    "PERFDB_SCHEMA",
    "PerfDBSchemaError",
    "PerfKey",
    "PerfDB",
    "shape_str",
    "mesh_str",
    "device_kind",
    "configured_db",
    "learned_ladder_for",
    "learned_block",
    "perfdb_counts",
]

PERFDB_SCHEMA = "hpx_tpu.perfdb.v1"

# sections a v1 document may carry (anything else = not our file)
_SECTIONS = ("observations", "stats", "ladders", "blocks")


class PerfDBSchemaError(RuntimeError):
    """A perfdb file that cannot be trusted: corrupt JSON, a missing
    or foreign ``schema`` stamp, or a version this build does not
    speak.  Always raised LOUDLY with the found version named —
    silently treating a stale store as empty would let an old ladder
    masquerade as a fresh miss."""


@dataclasses.dataclass(frozen=True)
class PerfKey:
    """One point on the banked cost surface.

    The key grammar is ``device|shape|kv_dtype|kernel|mesh`` —
    e.g. ``cpu|d32.h4.hd8.f40.l2.v64|bf16|gather|1``.  Dense (non-paged)
    servers use ``kv_dtype='-'`` and ``kernel='dense'``; a meshless
    server's mesh component is ``'1'``."""

    device: str
    shape: str
    kv_dtype: str = "-"
    kernel: str = "dense"
    mesh: str = "1"

    def __post_init__(self) -> None:
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if not v or "|" in v:
                raise ValueError(
                    f"PerfKey.{f.name}={v!r}: components must be "
                    "non-empty and '|'-free")

    def __str__(self) -> str:
        return "|".join((self.device, self.shape, self.kv_dtype,
                         self.kernel, self.mesh))

    @classmethod
    def parse(cls, s: str) -> "PerfKey":
        parts = s.split("|")
        if len(parts) != 5:
            raise ValueError(
                f"malformed perfdb key {s!r} (expected "
                "device|shape|kv_dtype|kernel|mesh)")
        return cls(*parts)


def shape_str(cfg) -> str:
    """Canonical model-shape component from a TransformerConfig —
    every field that changes program geometry, nothing that doesn't."""
    s = (f"d{cfg.d_model}.h{cfg.n_heads}.hd{cfg.head_dim}"
         f".f{cfg.d_ff}.l{cfg.n_layers}.v{cfg.vocab}")
    kv = getattr(cfg, "kv_heads", cfg.n_heads)
    if kv != cfg.n_heads:
        s += f".kv{kv}"
    ne = getattr(cfg, "n_experts", 0)
    if ne:
        s += f".e{ne}"
    return s


def mesh_str(mesh) -> str:
    """``'1'`` for meshless; ``dp2xtp4``-style otherwise (axis order
    as declared — a transposed mesh is a different program)."""
    if mesh is None:
        return "1"
    try:
        return "x".join(f"{k}{v}" for k, v in mesh.shape.items())
    except Exception:
        return "mesh"


def device_kind() -> str:
    """Sanitized accelerator kind (``'TPU v4'`` -> ``tpu_v4``);
    falls back to the jax backend name, then ``'cpu'``."""
    try:
        import jax
        try:
            kind = jax.devices()[0].device_kind
        except Exception:
            kind = jax.default_backend()
        return "".join(c if c.isalnum() else "_"
                       for c in str(kind).strip().lower()) or "cpu"
    except Exception:
        return "cpu"


def _default_stamps() -> Dict[str, Any]:
    """bench.py's provenance discipline, computed from the live
    backend: rows measured off-TPU are builder-session, never
    on-chip — see the ROADMAP tunnel-backlog note."""
    try:
        import jax
        onchip = jax.default_backend() == "tpu"
    except Exception:
        onchip = False
    return {"onchip": onchip,
            "provenance": "on-chip" if onchip else "builder-session"}


def _obs_id(row: Dict[str, Any]) -> str:
    """Content hash over the identity-bearing fields — NOT the whole
    row, so a re-banked identical measurement from another process
    dedups instead of double-counting, while distinct values of the
    same metric coexist."""
    basis = json.dumps(
        [row.get("key"), row.get("metric"), row.get("program"),
         row.get("value"), row.get("n"), row.get("provenance"),
         row.get("source"), row.get("pid"), row.get("seq")],
        sort_keys=True, separators=(",", ":"))
    return hashlib.sha1(basis.encode()).hexdigest()[:16]


def _merge_stats(a: Dict[str, Any], b: Dict[str, Any]) -> Dict[str, Any]:
    return {
        "n": a.get("n", 0) + b.get("n", 0),
        "sum": a.get("sum", 0.0) + b.get("sum", 0.0),
        "sumsq": a.get("sumsq", 0.0) + b.get("sumsq", 0.0),
        "min": min(a.get("min", math.inf), b.get("min", math.inf)),
        "max": max(a.get("max", -math.inf), b.get("max", -math.inf)),
        "onchip_n": a.get("onchip_n", 0) + b.get("onchip_n", 0),
    }


def _pick_rev(a: Dict[str, Any], b: Dict[str, Any]) -> Dict[str, Any]:
    """Deterministic winner for derived sections: higher ``rev``
    wins; equal revs tie-break on canonical content so the merge is
    writer-order independent."""
    ra, rb = int(a.get("rev", 0)), int(b.get("rev", 0))
    if ra != rb:
        return a if ra > rb else b
    ja = json.dumps(a, sort_keys=True)
    jb = json.dumps(b, sort_keys=True)
    return a if ja >= jb else b


class PerfDB:
    """One store instance.  Thread-safe; merge-safe across processes
    via the read-union-replace ``save()``."""

    def __init__(self, path: Optional[str] = None) -> None:
        self.path = path
        self._lock = threading.RLock()
        self.observations: List[Dict[str, Any]] = []
        self.stats: Dict[str, Dict[str, Any]] = {}
        self.ladders: Dict[str, Dict[str, Any]] = {}
        self.blocks: Dict[str, Dict[str, Any]] = {}
        # ids of rows compact() folded into stats — merge tombstones,
        # so a concurrent writer still holding the raw row cannot
        # re-add what a summary already counts (16 hex chars/row, ~10x
        # smaller than the row it replaces)
        self.folded: set = set()
        self._seq = 0          # per-instance tiebreaker for obs ids
        if path and os.path.exists(path):
            doc = self._read(path)
            self._adopt(doc)

    # -- (de)serialization --------------------------------------------------

    @staticmethod
    def _read(path: str) -> Dict[str, Any]:
        try:
            with open(path) as f:
                doc = json.load(f)
        except ValueError as e:
            raise PerfDBSchemaError(
                f"perfdb {path!r} is corrupt (not valid JSON: {e}); "
                "refusing to treat it as empty — move it aside to "
                "start fresh") from e
        if not isinstance(doc, dict):
            raise PerfDBSchemaError(
                f"perfdb {path!r} is not a JSON object; refusing")
        found = doc.get("schema")
        if found != PERFDB_SCHEMA:
            raise PerfDBSchemaError(
                f"perfdb {path!r} has schema {found!r}; this build "
                f"speaks {PERFDB_SCHEMA!r} only — refusing to read a "
                "version it cannot interpret (re-derive the store "
                "with benchmarks/ladder_search.py)")
        return doc

    def _adopt(self, doc: Dict[str, Any]) -> None:
        with self._lock:
            self.observations = list(doc.get("observations", []))
            self.stats = dict(doc.get("stats", {}))
            self.ladders = dict(doc.get("ladders", {}))
            self.blocks = dict(doc.get("blocks", {}))
            self.folded = set(doc.get("folded", []))

    def to_doc(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "schema": PERFDB_SCHEMA,
                "observations": list(self.observations),
                "stats": {k: dict(v) for k, v in self.stats.items()},
                "ladders": {k: dict(v) for k, v in self.ladders.items()},
                "blocks": {k: dict(v) for k, v in self.blocks.items()},
                "folded": sorted(self.folded),
            }

    def save(self, path: Optional[str] = None) -> str:
        """Merge-safe persist: re-read the file, union observations by
        id, add stats summaries, keep the higher-rev derived entries,
        then atomic tmp+rename.  Concurrent writers converge to the
        union — neither's observation log is lost."""
        path = path or self.path
        if not path:
            raise ValueError("PerfDB.save() needs a path")
        with self._lock:
            merged = self.to_doc()
            if os.path.exists(path):
                try:
                    disk = self._read(path)
                except PerfDBSchemaError:
                    raise
                merged = _merge_docs(disk, merged)
                self._adopt(merged)
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=d, prefix=".perfdb.",
                                       suffix=".tmp")
            try:
                with os.fdopen(fd, "w") as f:
                    json.dump(merged, f, indent=1, sort_keys=True)
                    f.write("\n")
                os.replace(tmp, path)
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
            self.path = path
        return path

    # -- producers ----------------------------------------------------------

    def observe(self, key, metric: str, value: float, n: int = 1,
                program: Optional[str] = None, source: str = "",
                onchip: Optional[bool] = None,
                provenance: Optional[str] = None) -> Dict[str, Any]:
        """Append one measurement.  ``key`` is a PerfKey or its string
        form; ``metric`` names what was measured (``compile_s``,
        ``exec_p50_s``, ``warm_tok_s``, ``block_ms``...); ``n`` is the
        sample count behind ``value`` (medians arrive pre-folded).
        Provenance defaults from the live backend per bench.py's
        stamps; pass explicitly when re-banking foreign rows."""
        stamps = _default_stamps()
        if onchip is not None:
            stamps["onchip"] = bool(onchip)
            stamps["provenance"] = (provenance if provenance is not None
                                    else ("on-chip" if onchip
                                          else "builder-session"))
        elif provenance is not None:
            stamps["provenance"] = provenance
            stamps["onchip"] = provenance == "on-chip"
        with self._lock:
            self._seq += 1
            row: Dict[str, Any] = {
                "key": str(key), "metric": str(metric),
                "value": float(value), "n": int(n),
                "source": source, "pid": os.getpid(),
                "seq": self._seq, "measured_at": time.time(),
            }
            if program is not None:
                row["program"] = str(program)
            row.update(stamps)
            row["id"] = _obs_id(row)
            self.observations.append(row)
            return row

    def record_ladder(self, key, ladder: Dict[str, Any]) -> None:
        """Install a derived ladder proposal for ``key``; bumps rev
        past whatever is already stored so the new proposal wins the
        next merge."""
        k = str(key)
        with self._lock:
            prev = self.ladders.get(k, {})
            entry = dict(ladder)
            entry["rev"] = int(prev.get("rev", 0)) + 1
            self.ladders[k] = entry

    def record_block(self, bkey: str, entry: Dict[str, Any]) -> None:
        with self._lock:
            prev = self.blocks.get(bkey, {})
            e = dict(entry)
            e["rev"] = int(prev.get("rev", 0)) + 1
            self.blocks[bkey] = e

    # -- compaction + cost models -------------------------------------------

    def compact(self, keep: int = 64) -> int:
        """Fold all but the newest ``keep`` observations per
        (key, metric) into the ``stats`` summaries.  Returns rows
        folded.  Sample counts and dispersion survive; raw rows are
        gone — compaction is what keeps a long-lived store O(keys)
        instead of O(runs)."""
        folded = 0
        with self._lock:
            bykm: Dict[str, List[Dict[str, Any]]] = {}
            for row in self.observations:
                bykm.setdefault(
                    f"{row['key']}::{row['metric']}", []).append(row)
            kept: List[Dict[str, Any]] = []
            for skey, rows in bykm.items():
                old, new = rows[:-keep] if keep else rows, \
                    rows[-keep:] if keep else []
                if old:
                    summ = self.stats.get(skey, {})
                    for row in old:
                        v, n = float(row["value"]), int(row.get("n", 1))
                        summ = _merge_stats(summ, {
                            "n": n, "sum": v * n, "sumsq": v * v * n,
                            "min": v, "max": v,
                            "onchip_n": n if row.get("onchip") else 0,
                        })
                    self.stats[skey] = summ
                    self.folded.update(
                        r.get("id", "") for r in old)
                    folded += len(old)
                kept.extend(new)
            kept.sort(key=lambda r: (r.get("measured_at", 0.0),
                                     r.get("id", "")))
            self.observations = kept
        return folded

    def model(self, key, metric: str) -> Dict[str, Any]:
        """Cost model for (key, metric): sample count, mean, std
        (dispersion), min/max, and how many samples were on-chip —
        folded summaries and live rows combined."""
        skey = f"{key}::{metric}"
        with self._lock:
            summ = dict(self.stats.get(skey, {}))
            agg = {"n": 0, "sum": 0.0, "sumsq": 0.0,
                   "min": math.inf, "max": -math.inf, "onchip_n": 0}
            if summ:
                agg = _merge_stats(agg, summ)
            for row in self.observations:
                if row["key"] == str(key) and row["metric"] == metric:
                    v, n = float(row["value"]), int(row.get("n", 1))
                    agg = _merge_stats(agg, {
                        "n": n, "sum": v * n, "sumsq": v * v * n,
                        "min": v, "max": v,
                        "onchip_n": n if row.get("onchip") else 0,
                    })
        n = agg["n"]
        if not n:
            return {"n": 0}
        mean = agg["sum"] / n
        var = max(0.0, agg["sumsq"] / n - mean * mean)
        return {"n": n, "mean": mean, "std": math.sqrt(var),
                "min": agg["min"], "max": agg["max"],
                "onchip_n": agg["onchip_n"]}

    def program_models(self, key, metric: str
                       ) -> Dict[str, Dict[str, Any]]:
        """Per-program cost models for (key, metric), from the live
        observation rows only — folded summaries drop the program axis
        by design, and compaction keeps the newest rows per
        (key, metric), so these models track the most recent runs.
        Returns ``{program: {n, mean, min, max}}``, sorted by program
        name (deterministic for the offline search)."""
        ks = str(key)
        agg: Dict[str, Dict[str, float]] = {}
        with self._lock:
            for row in self.observations:
                if row["key"] != ks or row["metric"] != metric \
                        or "program" not in row:
                    continue
                v, n = float(row["value"]), int(row.get("n", 1))
                a = agg.setdefault(str(row["program"]), {
                    "n": 0.0, "sum": 0.0,
                    "min": math.inf, "max": -math.inf})
                a["n"] += n
                a["sum"] += v * n
                a["min"] = min(a["min"], v)
                a["max"] = max(a["max"], v)
        return {p: {"n": int(a["n"]), "mean": a["sum"] / a["n"],
                    "min": a["min"], "max": a["max"]}
                for p, a in sorted(agg.items()) if a["n"]}

    # -- consumers ----------------------------------------------------------

    def ladder(self, key) -> Optional[Dict[str, Any]]:
        with self._lock:
            e = self.ladders.get(str(key))
            return dict(e) if e else None

    def block(self, bkey: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            e = self.blocks.get(bkey)
            return dict(e) if e else None

    def counts(self) -> Dict[str, int]:
        """Distinct keys and observation rows (stats summaries count
        as their folded keys) — the /perfdb counter feed."""
        with self._lock:
            keys = {row["key"] for row in self.observations}
            keys.update(s.split("::", 1)[0] for s in self.stats)
            keys.update(self.ladders)
            return {"keys": len(keys),
                    "observations": len(self.observations)
                    + sum(int(s.get("n", 0))
                          for s in self.stats.values())}

    def metrics_for(self, key) -> List[str]:
        ks = str(key)
        with self._lock:
            out = {row["metric"] for row in self.observations
                   if row["key"] == ks}
            out.update(s.split("::", 1)[1] for s in self.stats
                       if s.split("::", 1)[0] == ks)
        return sorted(out)

    def keys(self) -> List[str]:
        with self._lock:
            out = {row["key"] for row in self.observations}
            out.update(s.split("::", 1)[0] for s in self.stats)
            out.update(self.ladders)
        return sorted(out)


def _merge_docs(a: Dict[str, Any], b: Dict[str, Any]) -> Dict[str, Any]:
    """Union two v1 docs: observations by id (append-only, lossless
    modulo folded tombstones), stats by addition, derived sections by
    rev."""
    folded = set(a.get("folded", [])) | set(b.get("folded", []))
    obs: Dict[str, Dict[str, Any]] = {}
    for row in list(a.get("observations", [])) + \
            list(b.get("observations", [])):
        rid = row.get("id") or _obs_id(row)
        if rid in folded:
            continue   # already counted by a stats summary
        obs.setdefault(rid, row)
    rows = sorted(obs.values(),
                  key=lambda r: (r.get("measured_at", 0.0),
                                 r.get("id", "")))
    stats: Dict[str, Dict[str, Any]] = {
        k: dict(v) for k, v in a.get("stats", {}).items()}
    for k, v in b.get("stats", {}).items():
        stats[k] = _merge_stats(stats[k], v) if k in stats else dict(v)
    out = {"schema": PERFDB_SCHEMA, "observations": rows,
           "stats": stats, "folded": sorted(folded)}
    for section in ("ladders", "blocks"):
        sa = dict(a.get(section, {}))
        for k, v in b.get(section, {}).items():
            sa[k] = _pick_rev(sa[k], v) if k in sa else dict(v)
        out[section] = sa
    return out


# ---------------------------------------------------------------------------
# configured singleton + boot-time lookups
# ---------------------------------------------------------------------------

_configured: Optional[PerfDB] = None
_configured_path: Optional[str] = None
_cfg_lock = threading.Lock()


def _rc():
    from ..core.config import runtime_config
    return runtime_config()


def configured_db(reload: bool = False) -> Optional[PerfDB]:
    """The process store at ``hpx.perfdb.path``, or None when unset.
    Cached per path; ``reload=True`` re-reads the file (tests, and
    consumers that want post-search state)."""
    global _configured, _configured_path
    path = (_rc().get("hpx.perfdb.path", "") or "").strip()
    if not path:
        return None
    with _cfg_lock:
        if reload or _configured is None or _configured_path != path:
            _configured = PerfDB(path)
            _configured_path = path
        return _configured


def reset_configured() -> None:
    """Drop the cached singleton (tests)."""
    global _configured, _configured_path
    with _cfg_lock:
        _configured = None
        _configured_path = None


# boot-time lookup tallies (the /perfdb hit/miss/stale counters)
_hits = 0
_misses = 0
_stale = 0


def _usable(entry: Optional[Dict[str, Any]], min_samples: int,
            allow_session: bool) -> str:
    """'hit' | 'miss' | 'stale' for a derived entry under the boot
    policy: enough samples, and on-chip provenance unless session
    rows are explicitly allowed."""
    if not entry:
        return "miss"
    if int(entry.get("samples", 0)) < min_samples:
        return "stale"
    if not entry.get("onchip", False) and not allow_session:
        return "stale"
    return "hit"


def learned_ladder_for(cfg, kv_dtype: str = "-",
                       kernel: str = "dense",
                       mesh=None) -> Optional[Dict[str, Any]]:
    """Boot-time ladder lookup for a server shape.  Returns the
    learned ladder dict on a usable hit, else None (the caller falls
    back byte-identically to the hand-picked constants).  Gated on
    ``hpx.perfdb.use_learned_ladders``; a hit needs >=
    ``hpx.perfdb.min_samples`` samples and on-chip provenance unless
    ``hpx.perfdb.allow_session=1``.  Every call lands in the
    /perfdb/{hits,misses,stale} counters."""
    global _hits, _misses, _stale
    rc = _rc()
    if not rc.get_bool("hpx.perfdb.use_learned_ladders", False):
        return None
    db = configured_db()
    if db is None:
        _misses += 1
        return None
    key = PerfKey(device_kind(), shape_str(cfg), kv_dtype, kernel,
                  mesh_str(mesh))
    entry = db.ladder(key)
    verdict = _usable(
        entry, rc.get_int("hpx.perfdb.min_samples", 3),
        rc.get_bool("hpx.perfdb.allow_session", False))
    if verdict == "hit":
        _hits += 1
        return entry
    if verdict == "stale":
        _stale += 1
    else:
        _misses += 1
    return None


def learned_block(head_dim: int, kv_dtype: str) -> Optional[int]:
    """Learned paged block size for (head_dim, kv_dtype), or None.
    Same gating as ladders; consumed by
    ``ops.attention_pallas.resolve_paged_block_src`` between the env
    override and the paged_blocks.json seed tier."""
    global _hits, _misses, _stale
    rc = _rc()
    if not rc.get_bool("hpx.perfdb.use_learned_ladders", False):
        return None
    db = configured_db()
    if db is None:
        _misses += 1
        return None
    entry = db.block(f"hd{head_dim}x{kv_dtype}")
    verdict = _usable(
        entry, rc.get_int("hpx.perfdb.min_samples", 3),
        rc.get_bool("hpx.perfdb.allow_session", False))
    if verdict == "hit":
        _hits += 1
        return int(entry["block_size"])
    if verdict == "stale":
        _stale += 1
    else:
        _misses += 1
    return None


def record_enabled() -> bool:
    """True when the live progprof hook should bank its table on
    stop (``hpx.perfdb.record=1`` and a path is configured)."""
    return (_rc().get_bool("hpx.perfdb.record", False)
            and bool((_rc().get("hpx.perfdb.path", "") or "").strip()))


# attribution key for the live progprof producer: the last server to
# boot while recording was on names the (device, shape, kv_dtype,
# kernel, mesh) point its programs' costs belong to.  Falls back to a
# process-scoped pseudo-shape, so orphan programs still land in the
# log with provenance instead of vanishing.
_live_key: Optional[str] = None


def note_live_key(key) -> None:
    global _live_key
    _live_key = str(key)


def live_key() -> str:
    return _live_key or str(PerfKey(device_kind(), "proc"))


def bank_profile(db: "PerfDB", table: Dict[str, Any],
                 key) -> int:
    """Fold one progprof ``profile_table()`` into the observation log
    under ``key``: per-program mean compile seconds (n = compiles)
    and median execute seconds (n = calls).  Returns rows banked;
    caller saves."""
    banked = 0
    for row in table.get("programs", []):
        if row.get("compiles"):
            db.observe(key, "compile_s",
                       row["compile_s"] / max(1, row["compiles"]),
                       n=int(row["compiles"]), program=row["key"],
                       source="progprof")
            banked += 1
        if row.get("calls"):
            db.observe(key, "exec_p50_s", row["p50_s"],
                       n=int(row["calls"]), program=row["key"],
                       source="progprof")
            banked += 1
    return banked


def perfdb_counts() -> Dict[str, int]:
    """Counter feed: store sizes (0s when no store is configured)
    plus the process lookup tallies."""
    db = None
    try:
        db = configured_db()
    except PerfDBSchemaError:
        pass   # a corrupt store still answers counters (as empty)
    sizes = db.counts() if db is not None else \
        {"keys": 0, "observations": 0}
    return {**sizes, "hits": _hits, "misses": _misses,
            "stale": _stale}


_counters_on = False


def ensure_counters() -> None:
    """Register /perfdb{locality#N/total}/{keys,observations,hits,
    misses,stale} (idempotent) — CallbackCounters over
    ``perfdb_counts()``, so discovery always sees live values."""
    global _counters_on
    if _counters_on:
        return
    from . import performance_counters as pc

    def _mk(field: str):
        return pc.CallbackCounter(
            lambda f=field: float(perfdb_counts()[f]))

    for field in ("keys", "observations", "hits", "misses", "stale"):
        pc.register_counter(
            pc.counter_name("perfdb", field), _mk(field))
    _counters_on = True
