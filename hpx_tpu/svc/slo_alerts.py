"""SRE-style multi-window multi-burn-rate SLO alerting.

A latency SLO ("99% of requests see e2e <= 1s") grants an error
budget: the 1% of requests allowed over threshold.  The *burn rate*
is how fast a window of traffic spends that budget::

    burn(W) = bad_fraction(W) / (1 - target)

``burn == 1`` spends exactly the budget; ``burn == 14.4`` over a 5m
window is the classic "a 30-day budget gone in two days" page signal.
An alert fires only when BOTH a fast window (default 5m) and a slow
window (default 1h) exceed their burn factors — the fast window gives
low detection latency, the slow window gates flapping on brief blips —
and clears when the fast window recovers.

Mechanics: :class:`SloAlerts` keeps a ring of timestamped
``HistogramCounter.snapshot()``s per rule and computes windowed bad
fractions from ``delta()`` bucket counts directly — cumulative sums
over a detached window copy, never ``quantile()`` on the live
histogram (hpxlint HPX023 bans that O(buckets)-under-load scan from
hot paths).  The evaluator ticks at the same serving ``_flush()``
boundary the AdaptiveTuner uses, rate-limited to
``hpx.obs.alert_interval_s``; when ``hpx.obs.alerts=0`` the server
holds ``None`` and the flush path pays one is-None test (the
``hpx.trace.*`` zero-overhead discipline).

Firing increments the ``/serving{...}/alerts/*`` counters, captures a
flight bundle tagged ``slo_alert`` (via the ``on_fire`` hook), and —
with ``hpx.obs.alert_trace_dump`` — dumps the live trace ring next to
the bundle.
"""

from __future__ import annotations

import dataclasses
import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import tracing

__all__ = [
    "SloRule",
    "SloAlerts",
    "DEFAULT_RULES",
    "parse_rules",
    "from_config",
    "server_alerts",
    "health_state",
]


def _cfg():
    from ..core.config import runtime_config
    return runtime_config()


@dataclasses.dataclass(frozen=True)
class SloRule:
    """One latency objective over one histogram family."""

    hist: str           # LATENCY_KEYS family, e.g. "e2e"
    threshold_s: float  # a sample at/under this is a good event
    target: float       # fraction of samples that must be good

    @property
    def budget(self) -> float:
        return max(1e-9, 1.0 - self.target)

    @property
    def name(self) -> str:
        return f"{self.hist}<={self.threshold_s:g}s@{self.target:g}"


# the built-in objectives when hpx.obs.alert_rules is empty: e2e for
# the user-visible contract, decode_stall for the inter-token signal
# the tuner also optimizes
DEFAULT_RULES: Tuple[SloRule, ...] = (
    SloRule("e2e", 1.0, 0.95),
    SloRule("decode_stall", 0.25, 0.99),
)


def parse_rules(spec: str) -> Tuple[SloRule, ...]:
    """``hpx.obs.alert_rules`` grammar: csv of
    ``hist:threshold_s:target`` triples; empty selects
    :data:`DEFAULT_RULES`."""
    spec = (spec or "").strip()
    if not spec:
        return DEFAULT_RULES
    out: List[SloRule] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        bits = part.split(":")
        if len(bits) != 3:
            raise ValueError(
                f"hpx.obs.alert_rules entries are hist:threshold_s:"
                f"target, got {part!r}")
        out.append(SloRule(bits[0].strip(), float(bits[1]),
                           float(bits[2])))
    return tuple(out)


class _RuleState:
    __slots__ = ("ring", "state", "fired", "cleared",
                 "burn_fast", "burn_slow", "last_eval")

    def __init__(self) -> None:
        # (t, snapshot) ring, oldest first, pruned to the slow window
        self.ring: List[Tuple[float, Dict[str, Any]]] = []
        self.state = "ok"            # ok | alerting
        self.fired = 0
        self.cleared = 0
        self.burn_fast = 0.0
        self.burn_slow = 0.0
        self.last_eval = 0.0


class SloAlerts:
    """Burn-rate evaluator over a dict of live histograms.

    Pure in its inputs: the same snapshot/clock sequence produces the
    same fire/clear decisions (the burn-rate determinism test runs it
    twice and compares decision logs).  ``clock`` is injectable for
    exactly that reason; live servers use ``time.monotonic``."""

    def __init__(self, hists: Dict[str, Any],
                 rules: Tuple[SloRule, ...] = DEFAULT_RULES, *,
                 fast_s: float = 300.0, slow_s: float = 3600.0,
                 burn_fast: float = 14.4, burn_slow: float = 6.0,
                 interval_s: float = 1.0,
                 rates: Optional[Dict[str, Any]] = None,
                 on_fire: Optional[Callable[[str, Dict[str, Any]],
                                            Any]] = None,
                 trace_dump: bool = False,
                 clock: Callable[[], float] = time.monotonic,
                 name: str = "serving") -> None:
        self.name = name
        self.hists = hists
        self.rules = tuple(rules)
        self.fast_s = float(fast_s)
        self.slow_s = max(float(slow_s), self.fast_s)
        self.burn_fast = float(burn_fast)
        self.burn_slow = float(burn_slow)
        self.interval_s = max(0.0, float(interval_s))
        self.rates = dict(rates or {})
        self.on_fire = on_fire
        self.trace_dump = bool(trace_dump)
        self.clock = clock
        self.evals = 0
        self.fired = 0
        self.cleared = 0
        self._next_eval = 0.0
        self._state: Dict[str, _RuleState] = {
            r.name: _RuleState() for r in self.rules}
        # decision log for determinism tests and /healthz history
        self.decisions: List[Dict[str, Any]] = []
        _live.add(self)

    # -- burn math ----------------------------------------------------

    @staticmethod
    def _bad_fraction(hist: Any, cur: Dict[str, Any],
                      base: Dict[str, Any],
                      threshold_s: float) -> Tuple[float, int]:
        """Fraction of samples recorded between ``base`` and ``cur``
        that exceeded the threshold, from delta bucket counts alone.
        The bucket containing the threshold counts as good (its upper
        bound may exceed the threshold by up to one gamma step — a
        deterministic, slightly forgiving boundary)."""
        counts = [max(0, int(a) - int(b))
                  for a, b in zip(cur["counts"], base["counts"])]
        total = sum(counts)
        if not total:
            return 0.0, 0
        good_hi = hist._index(threshold_s)
        bad = sum(counts[good_hi + 1:])
        return bad / total, total

    def _window_base(self, st: _RuleState, now: float,
                     window_s: float) -> Dict[str, Any]:
        """Newest ring snapshot at/older than the window start; the
        oldest available when the window is not yet spanned (partial
        windows burn at the observed rate — honest at startup)."""
        cut = now - window_s
        base = st.ring[0][1]
        for t, snap in st.ring:
            if t <= cut:
                base = snap
            else:
                break
        return base

    # -- ticking ------------------------------------------------------

    def maybe_tick(self) -> Optional[List[Dict[str, Any]]]:
        """Flush-boundary entry point: cheap clock compare between
        evaluations (the flush loop ticks far faster than SLO state
        moves)."""
        now = self.clock()
        if now < self._next_eval:
            return None
        self._next_eval = now + self.interval_s
        return self.evaluate(now)

    def evaluate(self, now: Optional[float] = None
                 ) -> List[Dict[str, Any]]:
        """One evaluation of every rule; returns the fire/clear
        transitions it produced (empty = steady state)."""
        if now is None:
            now = self.clock()
        self.evals += 1
        out: List[Dict[str, Any]] = []
        for rule in self.rules:
            hist = self.hists.get(rule.hist)
            if hist is None:
                continue
            st = self._state[rule.name]
            cur = hist.snapshot()
            st.ring.append((now, cur))
            # prune: keep exactly one snapshot older than the slow
            # window so _window_base always has a boundary anchor
            cut = now - self.slow_s
            while len(st.ring) > 2 and st.ring[1][0] <= cut:
                st.ring.pop(0)
            frac_f, n_f = self._bad_fraction(
                hist, cur, self._window_base(st, now, self.fast_s),
                rule.threshold_s)
            frac_s, n_s = self._bad_fraction(
                hist, cur, self._window_base(st, now, self.slow_s),
                rule.threshold_s)
            st.burn_fast = frac_f / rule.budget
            st.burn_slow = frac_s / rule.budget
            st.last_eval = now
            if st.state == "ok":
                if n_f and st.burn_fast >= self.burn_fast \
                        and st.burn_slow >= self.burn_slow:
                    st.state = "alerting"
                    st.fired += 1
                    self.fired += 1
                    out.append(self._transition(
                        "fire", rule, st, now, n_f, n_s))
            elif st.burn_fast < self.burn_fast:
                st.state = "ok"
                st.cleared += 1
                self.cleared += 1
                out.append(self._transition(
                    "clear", rule, st, now, n_f, n_s))
        return out

    def _transition(self, action: str, rule: SloRule, st: _RuleState,
                    now: float, n_fast: int,
                    n_slow: int) -> Dict[str, Any]:
        info = {
            "action": action, "rule": rule.name, "hist": rule.hist,
            "threshold_s": rule.threshold_s, "target": rule.target,
            "burn_fast": round(st.burn_fast, 6),
            "burn_slow": round(st.burn_slow, 6),
            "window_fast_s": self.fast_s, "window_slow_s": self.slow_s,
            "samples_fast": n_fast, "samples_slow": n_slow,
            "t": now,
            "rates": {k: float(r.rate())
                      for k, r in self.rates.items()},
        }
        self.decisions.append(info)
        with tracing.span("serving.slo_alert", "serving",
                          action=action, rule=rule.name,
                          burn_fast=info["burn_fast"],
                          burn_slow=info["burn_slow"]):
            pass
        if action == "fire":
            if self.on_fire is not None:
                try:
                    self.on_fire(rule.name, info)
                except Exception:  # alerting must never break serving
                    pass
            if self.trace_dump:
                self._dump_trace(rule)
        return info

    def _dump_trace(self, rule: SloRule) -> None:
        tr = tracing.active_tracer()
        if tr is None:
            return
        try:
            import os
            from . import flight, trace_export
            d = flight.flight_dir()
            os.makedirs(d, exist_ok=True)
            path = os.path.join(
                d, f"alert-trace-{os.getpid()}-{self.fired:03d}.json")
            trace_export.write_chrome_trace(path, tr)
        except Exception:
            pass

    # -- reading ------------------------------------------------------

    def active(self) -> int:
        """Rules currently in the alerting state."""
        return sum(1 for st in self._state.values()
                   if st.state == "alerting")

    def state(self) -> Dict[str, Any]:
        """JSON-safe burn/FSM state for /healthz and /statusz."""
        return {
            "name": self.name,
            "active": self.active(),
            "evals": self.evals,
            "fired": self.fired,
            "cleared": self.cleared,
            "windows": {"fast_s": self.fast_s, "slow_s": self.slow_s,
                        "burn_fast": self.burn_fast,
                        "burn_slow": self.burn_slow},
            "rules": {
                r.name: {
                    "hist": r.hist,
                    "threshold_s": r.threshold_s,
                    "target": r.target,
                    "state": self._state[r.name].state,
                    "fired": self._state[r.name].fired,
                    "cleared": self._state[r.name].cleared,
                    "burn_fast": round(
                        self._state[r.name].burn_fast, 6),
                    "burn_slow": round(
                        self._state[r.name].burn_slow, 6),
                } for r in self.rules},
        }


# live evaluators, for /healthz aggregation — weak so an evaluator
# never outlives its server (same pattern as autotune._live)
_live: "weakref.WeakSet[SloAlerts]" = weakref.WeakSet()


def health_state() -> Dict[str, Any]:
    """Merged view across every live evaluator: the /healthz body.
    ``status`` is "alerting" when ANY rule anywhere is firing."""
    evals = sorted(_live, key=lambda a: a.name)
    active = sum(a.active() for a in evals)
    return {
        "status": "alerting" if active else "ok",
        "active": active,
        "evaluators": [a.state() for a in evals],
    }


def from_config(hists: Dict[str, Any], *,
                rates: Optional[Dict[str, Any]] = None,
                on_fire: Optional[Callable[[str, Dict[str, Any]],
                                           Any]] = None,
                name: str = "serving") -> Optional[SloAlerts]:
    """Build an evaluator from the ``hpx.obs.*`` knobs; None when
    ``hpx.obs.alerts`` is off — callers store the None and the flush
    path stays zero-overhead."""
    cfg = _cfg()
    if not cfg.get_bool("hpx.obs.alerts", False):
        return None
    return SloAlerts(
        hists,
        parse_rules(cfg.get("hpx.obs.alert_rules", "")),
        fast_s=cfg.get_float("hpx.obs.alert_fast_s", 300.0),
        slow_s=cfg.get_float("hpx.obs.alert_slow_s", 3600.0),
        burn_fast=cfg.get_float("hpx.obs.alert_burn_fast", 14.4),
        burn_slow=cfg.get_float("hpx.obs.alert_burn_slow", 6.0),
        interval_s=cfg.get_float("hpx.obs.alert_interval_s", 1.0),
        rates=rates, on_fire=on_fire,
        trace_dump=cfg.get_bool("hpx.obs.alert_trace_dump", False),
        name=name)


def server_alerts(srv: Any) -> Optional[SloAlerts]:
    """Bind an evaluator to a live ContinuousServer: its SLO
    histograms and token RateCounter feed the burn math, and a firing
    alert captures a flight bundle tagged ``slo_alert`` carrying the
    request timeline (the bundle's ``extra`` holds the burn numbers).
    The closure holds the server weakly — the evaluator must not keep
    a dead server's KV pools alive."""
    ref = weakref.ref(srv)

    def _fire(rule_name: str, info: Dict[str, Any]) -> None:
        from . import flight
        s = ref()
        flight.record_fault(
            "slo_alert", site=f"slo/{rule_name}", rid=None,
            timeline=getattr(s, "timeline", None),
            extra=info)

    return from_config(
        srv.hist, rates={"tokens": srv._rate}, on_fire=_fire,
        name=f"serving/{getattr(srv, 'counter_instance', 'total')}")
