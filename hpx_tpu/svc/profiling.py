"""Profiler bridge — the APEX / ITT-notify analog (SURVEY.md §5.1).

Reference analog: libs/core/itt_notify (VTune task annotations around
scheduler events) and the APEX `util::external_timer` callbacks fired at
task create/start/stop in libs/core/threading_base.

TPU-first: two planes —
  * device plane: jax.profiler traces (Perfetto/XPlane) via
    `profile_trace(logdir)` and `annotate(name)` (TraceAnnotation), which
    stamp host-side named ranges into the trace alongside XLA ops;
  * host plane: an external-timer registry; when enabled, the task pool
    invokes the registered callbacks at task submit/start/stop so an
    APEX-style tool (or the bundled TaskTimer) can build task statistics.
"""

from __future__ import annotations

import contextlib
from ..synchronization import Mutex
import time
from typing import Any, Callable, Dict, List, Optional

# ---------------------------------------------------------------------------
# external-timer registry (APEX hook analog)
# ---------------------------------------------------------------------------

_hooks_lock = Mutex()
_hooks: List[Any] = []      # objects with optional on_submit/on_start/on_stop

# Observer callbacks must never break tasks, so their exceptions are
# swallowed — but SILENT swallowing makes a broken hook (a TaskTimer
# whose on_stop raises, a tracer bug) invisible forever. Every swallow
# increments this counter, exported as the
# /runtime{...}/count/dropped-observer-callbacks performance counter.
_dropped_lock = Mutex()
_dropped_callbacks = 0


def note_observer_error() -> None:
    """Record one swallowed observer exception (also called by the
    threadpool's own observer guards)."""
    global _dropped_callbacks
    with _dropped_lock:
        _dropped_callbacks += 1


def dropped_callbacks() -> int:
    """Observer callbacks dropped (exception swallowed) so far."""
    return _dropped_callbacks


def reset_dropped_callbacks() -> None:
    global _dropped_callbacks
    with _dropped_lock:
        _dropped_callbacks = 0


def register_external_timer(hook: Any) -> None:
    """hook may define on_submit(fn), on_start(fn), on_stop(fn, seconds)."""
    # toggle under the same lock as the list mutation: otherwise a
    # concurrent register/last-unregister pair can interleave so the
    # observer ends disabled while _hooks is non-empty
    with _hooks_lock:
        if hook not in _hooks:
            _hooks.append(hook)
        _set_pool_instrumentation(bool(_hooks))


def unregister_external_timer(hook: Any) -> None:
    with _hooks_lock:
        if hook in _hooks:
            _hooks.remove(hook)
        _set_pool_instrumentation(bool(_hooks))


def _emit(event: str, *args: Any) -> None:
    with _hooks_lock:
        hooks = list(_hooks)
    for h in hooks:
        cb = getattr(h, f"on_{event}", None)
        if cb is not None:
            try:
                cb(*args)
            except Exception:  # noqa: BLE001 — observers must not break tasks
                note_observer_error()


def _set_pool_instrumentation(enable: bool) -> None:
    from ..runtime import threadpool
    threadpool.set_task_observer(_task_observer if enable else None)


def _unwrap(fn: Callable, args: tuple) -> Callable:
    """Attribute time to the user function, not scheduling shims.

    futures' async_ submits `_run_into(state, fn, args, kwargs)`; other
    wrappers are reported as-is."""
    name = getattr(fn, "__name__", "")
    if name == "_run_into" and len(args) >= 2 and callable(args[1]):
        return args[1]
    return fn


def _task_observer(event: str, fn: Callable, dt: Optional[float],
                   args: tuple = ()) -> None:
    target = _unwrap(fn, args)
    if event == "stop":
        _emit("stop", target, dt)
    else:
        _emit(event, target)


class TaskTimer:
    """Bundled external timer: per-function task counts + total seconds."""

    def __init__(self) -> None:
        self._lock = Mutex()
        self.stats: Dict[str, list] = {}   # name -> [count, total_s]

    @staticmethod
    def _name(fn: Callable) -> str:
        return getattr(fn, "__qualname__", repr(fn))

    def on_stop(self, fn: Callable, seconds: float) -> None:
        name = self._name(fn)
        with self._lock:
            st = self.stats.setdefault(name, [0, 0.0])
            st[0] += 1
            st[1] += seconds

    def top(self, k: int = 10) -> List[tuple]:
        with self._lock:
            rows = [(name, c, t) for name, (c, t) in self.stats.items()]
        return sorted(rows, key=lambda r: -r[2])[:k]


@contextlib.contextmanager
def task_timing():
    """Scoped TaskTimer: `with task_timing() as t: ...; t.top()`."""
    t = TaskTimer()
    register_external_timer(t)
    try:
        yield t
    finally:
        unregister_external_timer(t)


# ---------------------------------------------------------------------------
# device-plane bridges (jax.profiler)
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def profile_trace(logdir: str):
    """Capture a jax.profiler trace (view in Perfetto/TensorBoard)."""
    import jax
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named range visible in profiler traces (itt task annotation
    analog); usable as a context manager."""
    import jax
    return jax.profiler.TraceAnnotation(name)


def device_memory_stats(device_index: int = 0) -> Dict[str, Any]:
    import jax
    try:
        return dict(jax.devices()[device_index].memory_stats() or {})
    except Exception:  # noqa: BLE001
        return {}
