"""The live ops plane: a stdlib ``http.server`` endpoint an operator
(or a scraper) can hit while the fleet serves.

Everything before this module surfaced state as end-of-run artifacts;
a live fleet needs a port.  One background daemon thread runs a
``ThreadingHTTPServer`` (loopback by default) with five read-only
views:

``/varz``
    Prometheus text exposition of the whole counter registry, with
    content-type negotiation: an ``Accept: application/
    openmetrics-text`` scrape gets OpenMetrics 1.0 — tail-bucket
    exemplars on the ``_bucket`` rows and a ``# EOF`` terminator.
``/statusz``
    JSON: per-provider server/fleet/worker state (queue depths, live
    slots, autoscale state), the tuner flight snapshot, tier
    occupancy, and the dist heartbeat table.
``/tracez``
    The recent slowest completed spans sampled from the live trace
    ring (empty list when tracing is off).
``/flightz``
    The flight-bundle index (the same ``flight.bundle_index()`` the
    ``list`` CLI prints), and ``/flightz?fetch=<name>`` returns one
    bundle's JSON.
``/healthz``
    SLO burn state merged across live ``SloAlerts`` evaluators; HTTP
    503 while any alert is firing, so a load balancer can shed.

Wiring: ``ensure_opsplane()`` reads ``hpx.obs.port`` (``-1`` = off,
``0`` = ephemeral, ``>0`` = fixed) and starts the process-wide plane
once; ContinuousServer, DisaggRouter and FleetRouter register weakref
statusz providers on construction, so ONE router port exposes the
merged fleet view and a dead server silently drops out (the
cache/counters weakref discipline).
"""

from __future__ import annotations

import json
import os
import threading
import time
import weakref
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional
from urllib.parse import parse_qs, urlparse

from . import tracing
from ..synchronization import Mutex

__all__ = [
    "OpsPlane",
    "start_opsplane",
    "ensure_opsplane",
    "active_opsplane",
    "stop_opsplane",
    "register_provider",
]


def _cfg():
    from ..core.config import runtime_config
    return runtime_config()


def _heartbeat_table() -> Dict[str, str]:
    """ALIVE/SUSPECT/DEAD per known locality, {} outside a dist run."""
    try:
        from ..dist import runtime as _rt
        rt = getattr(_rt, "_runtime", None)
        if rt is None:
            return {}
        return {str(loc): rt.locality_state(loc)
                for loc in sorted(rt._table)}
    except Exception:
        return {}


class _Handler(BaseHTTPRequestHandler):
    # self.server is the _HTTPServer below, which carries the plane

    def log_message(self, fmt: str, *args: Any) -> None:
        pass                       # an ops scrape must not spam stderr

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, doc: Any, code: int = 200) -> None:
        body = json.dumps(doc, indent=1, default=repr).encode()
        self._send(code, body, "application/json; charset=utf-8")

    def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        try:
            url = urlparse(self.path)
            route = url.path.rstrip("/") or "/"
            plane = self.server.plane
            if route == "/varz":
                from . import metrics
                om, ctype = metrics.negotiate_exposition(
                    self.headers.get("Accept"))
                self._send(200, metrics.render_prometheus(
                    openmetrics=om).encode(), ctype)
            elif route == "/statusz":
                self._send_json(plane.statusz())
            elif route == "/tracez":
                self._send_json(plane.tracez())
            elif route == "/flightz":
                q = parse_qs(url.query)
                name = (q.get("fetch") or [None])[0]
                if name is None:
                    from . import flight
                    self._send_json({"bundles": flight.bundle_index()})
                else:
                    doc = plane.flight_fetch(name)
                    if doc is None:
                        self._send_json({"error": "no such bundle",
                                         "name": name}, code=404)
                    else:
                        self._send_json(doc)
            elif route == "/healthz":
                from . import slo_alerts
                doc = slo_alerts.health_state()
                self._send_json(
                    doc, code=503 if doc["status"] == "alerting"
                    else 200)
            elif route == "/":
                self._send_json({"endpoints": ["/varz", "/statusz",
                                               "/tracez", "/flightz",
                                               "/healthz"]})
            else:
                self._send_json({"error": "no such route",
                                 "path": route}, code=404)
        except BrokenPipeError:
            pass
        except Exception as e:  # a bad scrape must not kill the plane
            try:
                self._send_json({"error": repr(e)}, code=500)
            except Exception:
                pass


class _HTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True
    plane: "OpsPlane"


class OpsPlane:
    """One background HTTP endpoint; providers contribute /statusz
    sections.  Providers are named callables returning a JSON-safe
    dict (or None to skip); they are expected to close over weakrefs
    so the plane never pins a server."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self._srv = _HTTPServer((host, port), _Handler)
        self._srv.plane = self
        self.host = host
        self.port = int(self._srv.server_address[1])
        self.url = f"http://{host}:{self.port}"
        self.started = time.time()
        self._providers: "Dict[str, Callable[[], Any]]" = {}
        self._lock = Mutex()
        self._thread = threading.Thread(
            target=self._srv.serve_forever, name="hpx-opsplane",
            daemon=True)
        self._thread.start()

    # -- providers ----------------------------------------------------

    def add_provider(self, name: str,
                     fn: Callable[[], Any]) -> None:
        with self._lock:
            self._providers[name] = fn

    def remove_provider(self, name: str) -> None:
        with self._lock:
            self._providers.pop(name, None)

    # -- views --------------------------------------------------------

    def statusz(self) -> Dict[str, Any]:
        from . import autotune
        from ..cache import tier as _tier
        with self._lock:
            providers = dict(self._providers)
        out: Dict[str, Any] = {
            "wall_time": time.time(),
            "pid": os.getpid(),
            "uptime_s": round(time.time() - self.started, 3),
            "tune": autotune.flight_snapshot(),
            "tier": _tier.flight_snapshot(),
            "heartbeats": _heartbeat_table(),
            "providers": {},
        }
        dead: List[str] = []
        for name in sorted(providers):
            try:
                doc = providers[name]()
            except Exception as e:
                doc = {"error": repr(e)}
            if doc is None:        # weakref target died: prune
                dead.append(name)
                continue
            out["providers"][name] = doc
        for name in dead:
            self.remove_provider(name)
        return out

    def tracez(self, limit: int = 32) -> Dict[str, Any]:
        tr = tracing.active_tracer()
        if tr is None:
            return {"tracing": False, "spans": []}
        from . import trace_export
        return {
            "tracing": True,
            "dropped": tr.dropped,
            "spans": trace_export.slow_spans(tr.snapshot(), tr.t0,
                                             limit=limit),
        }

    def flight_fetch(self, name: str) -> Optional[Dict[str, Any]]:
        """One bundle by basename — constrained to real bundle names
        inside the flight dir (no path traversal from a URL)."""
        from . import flight
        name = os.path.basename(name)
        if not (name.startswith("flight-") and name.endswith(".json")):
            return None
        path = os.path.join(flight.flight_dir(), name)
        try:
            with open(path, encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    # -- lifecycle ----------------------------------------------------

    def close(self) -> None:
        try:
            self._srv.shutdown()
            self._srv.server_close()
        finally:
            self._thread.join(timeout=2.0)


# process-wide singleton, the same discipline as tracing._active
_plane: Optional[OpsPlane] = None


def start_opsplane(host: Optional[str] = None,
                   port: Optional[int] = None) -> OpsPlane:
    """Start (or return) the process-wide plane.  Explicit arguments
    override the ``hpx.obs.host``/``hpx.obs.port`` knobs — tests pass
    ``port=0`` for an ephemeral OS-assigned port."""
    global _plane
    if _plane is not None:
        return _plane
    cfg = _cfg()
    if host is None:
        host = cfg.get("hpx.obs.host", "127.0.0.1") or "127.0.0.1"
    if port is None:
        port = max(0, cfg.get_int("hpx.obs.port", -1))
    _plane = OpsPlane(host, port)
    return _plane


def ensure_opsplane() -> Optional[OpsPlane]:
    """Config-gated start: None (and no socket, no thread) unless
    ``hpx.obs.port`` >= 0.  Servers call this from __init__; the
    is-None result is the zero-overhead gate."""
    if _plane is not None:
        return _plane
    if _cfg().get_int("hpx.obs.port", -1) < 0:
        return None
    return start_opsplane()


def active_opsplane() -> Optional[OpsPlane]:
    return _plane


def stop_opsplane() -> None:
    global _plane
    if _plane is not None:
        _plane.close()
        _plane = None


def register_provider(name: str, owner: Any,
                      fn: Callable[[Any], Any]) -> None:
    """Attach a weakref statusz provider for ``owner`` to the active
    plane (no-op when the plane is off).  ``fn(owner)`` builds the
    section; after ``owner`` dies the provider returns None once and
    is pruned."""
    plane = active_opsplane()
    if plane is None:
        return
    ref = weakref.ref(owner)

    def provider() -> Any:
        o = ref()
        if o is None:
            return None
        return fn(o)

    plane.add_provider(name, provider)
