"""Leveled runtime logging (SURVEY.md §2.5 'logging').

Reference analog: libs/core/logging — printf-style leveled logs routed
to destinations, enabled by --hpx:debug-hpx-log / ini keys. Here: a thin
layer over stdlib logging wired to the layered config
(hpx.logging.level, hpx.logging.destination), with the locality id
stamped into every record the way HPX prefixes its log lines.
"""

from __future__ import annotations

import logging
import sys
from ..synchronization import Mutex
from typing import Optional

_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "always": logging.CRITICAL,
    "off": logging.CRITICAL + 10,
}

_configured = False
_lock = Mutex()


class _LocalityFilter(logging.Filter):
    def filter(self, record: logging.LogRecord) -> bool:
        from ..dist.runtime import find_here
        record.locality = find_here()
        return True


def _configure() -> None:
    global _configured
    with _lock:
        if _configured:
            return
        from ..core.config import runtime_config
        cfg = runtime_config()
        root = logging.getLogger("hpx_tpu")
        level = _LEVELS.get(cfg.get("hpx.logging.level", "warning"),
                            logging.WARNING)
        root.setLevel(level)
        dest = cfg.get("hpx.logging.destination", "")
        handler: logging.Handler
        if dest in ("", "cerr", "stderr"):
            handler = logging.StreamHandler(sys.stderr)
        elif dest in ("cout", "stdout"):
            handler = logging.StreamHandler(sys.stdout)
        else:
            handler = logging.FileHandler(dest)
        handler.setFormatter(logging.Formatter(
            "[%(asctime)s] [locality#%(locality)s] [%(levelname)s] "
            "[%(name)s] %(message)s"))
        handler.addFilter(_LocalityFilter())
        root.addHandler(handler)
        root.propagate = False
        _configured = True


def get_logger(module: str = "runtime") -> logging.Logger:
    """Module loggers hang under 'hpx_tpu.' (agas, parcel, threads...)."""
    _configure()
    return logging.getLogger(f"hpx_tpu.{module}")


def set_log_level(level: str) -> None:
    """--hpx:debug-hpx-log analog at runtime; level name per HPX."""
    _configure()
    if level not in _LEVELS:
        raise ValueError(f"unknown log level {level!r}; "
                         f"one of {sorted(_LEVELS)}")
    logging.getLogger("hpx_tpu").setLevel(_LEVELS[level])
