"""Fault flight recorder: a bounded black-box for post-mortems.

When a typed fault fires — a shed, a worker failover, a retry
exhaustion, an autoscale drain — the counters that describe the fleet's
state are about to be overwritten by recovery.  This module persists a
schema-versioned JSON bundle at the moment of the fault: the last-N
trace spans, a full counter + histogram registry snapshot, the resolved
configuration, the program profile table, and the affected request's
timeline.  Wired through ``models/serving`` (``_shed_req``,
``_shed_everything``), ``models/disagg`` (worker failover, degrade),
``svc/fleet`` (autoscale drain) and ``svc/resiliency`` (replay
exhaustion).

Zero-cost discipline (same as tracing's ``active_tracer()`` None
check): the recorder allocates NOTHING until a capture fires —
``record_fault`` is the only entry point on fault paths, it is never
called per-step, and its disabled path is one config lookup.  Captures
never raise into the caller: a broken disk must not turn a shed into a
crash (failures count on :func:`dropped_count`).

Knobs (``hpx.flight.*``): ``enabled`` (default on), ``dir``
(``auto`` = ``<tmpdir>/hpx_tpu_flight``), ``max_bundles`` (oldest
pruned), ``spans`` (last-N trace spans per bundle).

One-shot live capture::

    python -m hpx_tpu.svc.flight dump [--out PATH]
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Any, Dict, List, Optional

__all__ = [
    "FLIGHT_SCHEMA",
    "record_fault",
    "build_bundle",
    "validate_bundle",
    "capture_count",
    "dropped_count",
    "reset_counts",
    "flight_dir",
    "bundle_index",
    "main",
]

FLIGHT_SCHEMA = "hpx_tpu.flight.v1"

# GIL-atomic capture accounting (Tracer.dropped discipline); the
# zero-cost-when-disarmed test asserts capture_count() stays 0 across a
# fault-free serving run.
_captures = 0
_dropped = 0
_seq = 0


def capture_count() -> int:
    return _captures


def dropped_count() -> int:
    return _dropped


def reset_counts() -> None:
    global _captures, _dropped
    _captures = 0
    _dropped = 0


def _cfg():
    from ..core.config import runtime_config
    return runtime_config()


def flight_dir() -> str:
    raw = _cfg().get("hpx.flight.dir", "auto") or "auto"
    if raw == "auto":
        return os.path.join(tempfile.gettempdir(), "hpx_tpu_flight")
    return raw


def _trace_spans(limit: int) -> List[Dict[str, Any]]:
    """Last-``limit`` events of the active tracer ring, decoded from
    the flat 8-tuples to JSON dicts ([] when tracing is off)."""
    from . import tracing
    tr = tracing.active_tracer()
    if tr is None:
        return []
    events = tr.snapshot()[-max(0, limit):]
    out: List[Dict[str, Any]] = []
    for ph, name, cat, ts, tid, id_, parent, args in events:
        ev: Dict[str, Any] = {"ph": ph, "name": name, "cat": cat,
                              "ts": ts, "tid": tid}
        if id_ is not None:
            ev["id"] = id_
        if parent is not None:
            ev["parent"] = parent
        if args is not None:
            # span args are dicts; "C" counter samples carry a bare
            # float in the same slot
            ev["args"] = dict(args) if isinstance(args, dict) else args
        out.append(ev)
    return out


def _config_dump() -> Dict[str, str]:
    cfg = _cfg()
    out: Dict[str, str] = {}
    for line in cfg.dump().splitlines():
        k, sep, v = line.partition(" = ")
        if sep:
            out[k] = v
    return out


def build_bundle(kind: str, site: Optional[str] = None,
                 rid: Any = None, error: Optional[BaseException] = None,
                 timeline: Any = None,
                 extra: Optional[Dict[str, Any]] = None
                 ) -> Dict[str, Any]:
    """Assemble (but do not persist) one flight bundle.  ``timeline``
    is an optional :class:`metrics.RequestTimeline`; with a ``rid`` its
    events for that request are captured."""
    from . import metrics, progprof
    cfg = _cfg()
    spans_n = cfg.get_int("hpx.flight.spans", 256)
    doc: Dict[str, Any] = {
        "schema": FLIGHT_SCHEMA,
        "wall_time": time.time(),
        "trigger": {
            "kind": kind,
            "site": site,
            "rid": rid if isinstance(rid, (int, str, type(None)))
            else repr(rid),
            "error_type": type(error).__name__
            if error is not None else None,
            "error": repr(error) if error is not None else None,
        },
        "spans": _trace_spans(spans_n),
        "counters": metrics.registry_snapshot("*"),
        "config": _config_dump(),
        "programs": progprof.profile_table(),
        "timeline": (timeline.events(rid)
                     if timeline is not None and rid is not None
                     else []),
    }
    # adaptive-tuner black box: every live tuner's decision log +
    # signal history, so a post-incident dump answers "what did the
    # tuner do leading up to this shed/failover" (and replays it —
    # autotune.replay). {} when no tuner is live; only runs inside a
    # bundle capture, so the zero-cost discipline holds.
    from . import autotune
    doc["tune"] = autotune.flight_snapshot()
    # host-tier state: occupancy + demote/promote/drop totals across
    # every live tier, so a shed bundle answers "was the cold tier
    # absorbing evictions or thrashing when this request died". {}
    # when no tier is live (the key stays optional, like tune).
    from ..cache import tier as _tier
    doc["tier"] = _tier.flight_snapshot()
    if extra:
        doc["extra"] = dict(extra)
    return doc


def _persist(doc: Dict[str, Any]) -> str:
    global _seq
    d = flight_dir()
    os.makedirs(d, exist_ok=True)
    kind = str(doc.get("trigger", {}).get("kind", "fault"))
    kind = "".join(ch if ch.isalnum() or ch in "-_" else "-"
                   for ch in kind) or "fault"
    while True:
        _seq += 1
        path = os.path.join(
            d, f"flight-{os.getpid()}-{_seq:05d}-{kind}.json")
        if not os.path.exists(path):
            break
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, default=repr)
    os.replace(tmp, path)
    _prune(d)
    return path


def _prune(d: str) -> None:
    keep = max(1, _cfg().get_int("hpx.flight.max_bundles", 8))
    try:
        bundles = sorted(
            (os.path.join(d, n) for n in os.listdir(d)
             if n.startswith("flight-") and n.endswith(".json")),
            key=os.path.getmtime)
    except OSError:
        return
    for path in bundles[:-keep] if len(bundles) > keep else []:
        try:
            os.remove(path)
        except OSError:
            pass


def record_fault(kind: str, site: Optional[str] = None, rid: Any = None,
                 error: Optional[BaseException] = None,
                 timeline: Any = None,
                 extra: Optional[Dict[str, Any]] = None) -> Optional[str]:
    """Capture and persist one bundle; returns its path, or None when
    disabled or the capture failed.  Never raises — this runs on fault
    paths where a second failure must not mask the first."""
    global _captures, _dropped
    try:
        if not _cfg().get_bool("hpx.flight.enabled", True):
            return None
        path = _persist(build_bundle(kind, site=site, rid=rid,
                                     error=error, timeline=timeline,
                                     extra=extra))
        _captures += 1
        return path
    except Exception:  # noqa: BLE001 — recorder must not break recovery
        _dropped += 1
        return None


# ---------------------------------------------------------------------------
# bundle index (the `list` CLI and the opsplane /flightz route share it)
# ---------------------------------------------------------------------------

def bundle_index(d: Optional[str] = None) -> List[Dict[str, Any]]:
    """Age-sorted (newest first) index of the on-disk bundles: name,
    age, trigger reason/site/rid, and schema version — enough for an
    operator to pick which bundle to fetch without opening each one.
    Unreadable files still index (an operator must see a truncated
    bundle exists), with ``error`` set."""
    d = flight_dir() if d is None else d
    try:
        names = [n for n in os.listdir(d)
                 if n.startswith("flight-") and n.endswith(".json")]
    except OSError:
        return []
    now = time.time()
    out: List[Dict[str, Any]] = []
    for name in names:
        path = os.path.join(d, name)
        entry: Dict[str, Any] = {"name": name, "path": path}
        try:
            entry["mtime"] = os.path.getmtime(path)
            entry["age_s"] = round(max(0.0, now - entry["mtime"]), 3)
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
            trig = doc.get("trigger") or {}
            entry["reason"] = trig.get("kind")
            entry["site"] = trig.get("site")
            entry["rid"] = trig.get("rid")
            entry["schema"] = doc.get("schema")
        except (OSError, ValueError) as e:
            entry["error"] = repr(e)
        out.append(entry)
    out.sort(key=lambda e: (-e.get("mtime", 0.0), e["name"]))
    return out


# ---------------------------------------------------------------------------
# schema validation (tests + CLI)
# ---------------------------------------------------------------------------

_REQUIRED_KEYS = ("schema", "wall_time", "trigger", "spans", "counters",
                  "config", "programs", "timeline")


def validate_bundle(doc: Dict[str, Any]) -> List[str]:
    """Structural check of one bundle; returns a list of problems
    (empty = valid)."""
    errs: List[str] = []
    if not isinstance(doc, dict):
        return ["bundle is not an object"]
    if doc.get("schema") != FLIGHT_SCHEMA:
        errs.append(f"schema {doc.get('schema')!r} != {FLIGHT_SCHEMA!r}")
    for k in _REQUIRED_KEYS:
        if k not in doc:
            errs.append(f"missing key {k!r}")
    trig = doc.get("trigger")
    if not isinstance(trig, dict) or "kind" not in trig:
        errs.append("trigger must be an object with a 'kind'")
    if not isinstance(doc.get("spans"), list):
        errs.append("spans must be a list")
    counters = doc.get("counters")
    if not (isinstance(counters, dict)
            and isinstance(counters.get("histograms"), dict)
            and isinstance(counters.get("counters"), dict)):
        errs.append("counters must hold 'histograms' and 'counters'")
    if not isinstance(doc.get("config"), dict):
        errs.append("config must be an object")
    progs = doc.get("programs")
    if progs is not None and not (
            isinstance(progs, dict)
            and isinstance(progs.get("programs"), list)):
        errs.append("programs must be null or a profile table")
    if not isinstance(doc.get("timeline"), list):
        errs.append("timeline must be a list")
    tune = doc.get("tune")
    if tune is not None and not isinstance(tune, dict):
        errs.append("tune must be absent or an object")
    tier = doc.get("tier")
    if tier is not None and not isinstance(tier, dict):
        errs.append("tier must be absent or an object")
    return errs


# ---------------------------------------------------------------------------
# one-shot CLI:  python -m hpx_tpu.svc.flight dump [--out PATH]
#                python -m hpx_tpu.svc.flight --list [--tail N]
# ---------------------------------------------------------------------------

def _print_index(tail: int) -> int:
    """The ``--list`` view: one line per bundle, newest first —
    exactly what the opsplane /flightz route serves as JSON."""
    entries = bundle_index()
    if tail > 0:
        entries = entries[:tail]
    for e in entries:
        if "error" in e:
            print(f"{e['name']}  error={e['error']}")
            continue
        print(f"{e['name']}  age={e['age_s']:.1f}s  "
              f"reason={e['reason']}  site={e['site']}  "
              f"rid={e['rid']}  schema={e['schema']}")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m hpx_tpu.svc.flight",
        description="fault flight recorder tools")
    ap.add_argument("--list", action="store_true", dest="list_",
                    help="print the age-sorted bundle index "
                         "(reason/rid/schema per line) and exit")
    ap.add_argument("--tail", type=int, default=0, metavar="N",
                    help="with --list: only the newest N bundles")
    sub = ap.add_subparsers(dest="cmd", required=False)
    dump = sub.add_parser("dump", help="capture one bundle right now")
    dump.add_argument("--out", default=None,
                      help="write here instead of hpx.flight.dir")
    args = ap.parse_args(argv)
    if args.list_:
        return _print_index(args.tail)
    if args.cmd is None:
        ap.print_usage()
        return 2
    if args.cmd == "dump":
        doc = build_bundle("manual", site="cli")
        if args.out:
            tmp = args.out + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1, default=repr)
            os.replace(tmp, args.out)
            path = args.out
        else:
            path = _persist(doc)
        problems = validate_bundle(doc)
        print(path)
        for p in problems:
            print(f"warning: {p}")
        return 0 if not problems else 1
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
