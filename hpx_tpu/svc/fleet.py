"""Fleet serving: prefix-cache-aware routing over N prefill × M
decode workers, with queue-depth autoscaling.

This is the ROADMAP's "millions of users" topology: PR 8's
disaggregated prefill/decode split and PR 10's mesh-sharded paged
serving composed behind one front end. A :class:`FleetRouter` fronts
*N* ``PrefillWorker``s and *M* ``DecodeWorker``s (each optionally
constructed with ``mesh=`` so its paged server runs under
``shard_map``), and replaces the base router's least-loaded placement
with **prefix-cache-aware** scoring — the AGAS move of treating
workers as named, queryable localities:

* every decode worker exposes a cheap **prefix digest** of its radix
  tree (``RadixCache.prefix_digest``: one 64-bit chain hash per
  retained prefix, MRU-first, truncated) pulled through the ordinary
  worker-call surface on a knob-set refresh interval;
* the router fingerprints each prompt once
  (``cache.radix.prefix_hashes``) and scores candidates by
  ``matched_blocks * w_prefix - eviction_rate * w_pressure`` — the
  longest-cached-prefix term sends Zipf-shared-prefix traffic where
  its KV blocks already live, the cache-pressure term steers away
  from workers whose trees are churning;
* a placement HIT becomes a prefill SAVING: the router pulls the
  matched rows off the chosen decode worker
  (``DecodeWorker.fetch_prefix`` →
  ``ContinuousServer.export_prefix_rows``), frames them as ordinary
  retained KV segments (shipped for receiver coverage AND retained
  for failover re-ship — the same machinery PR 8 replays through),
  and seeds the prefill worker's scratch so only the suffix
  recomputes. Tokens stay sha-identical to a single colocated
  ``generate()``; only the work moves.

Queue-depth autoscaling rounds it out: when the admission queue
crests ``scale_high`` the router mints a decode worker from the same
construction recipe (same mesh, same program-cache keys); when it
falls to ``scale_low`` and a worker sits idle, that worker DRAINS —
its in-flight requests re-dispatch through the failover path
(router state commits before every risky send, the rule PR 8
established at every cross-worker call site), then it closes and its
post-eviction block count folds into ``leaked_blocks()`` so retiring
a worker can never hide a leak.

Digest staleness only mis-scores placement, never correctness:
admission re-matches the worker's real tree, and a stale hit merely
fetches fewer rows than hoped.

Config (``hpx.serving.fleet.*``; all declared in
``core/config_schema.py``)::

    prefill_workers / decode_workers   default pool sizes (2 / 2)
    decode_pool_min / decode_pool_max  autoscale floor / ceiling (1 / 4)
    digest_entries                     digest hashes pulled per worker (64)
    digest_refresh_s                   digest freshness window (0.25)
    placement                          prefix | load
    w_prefix / w_pressure              placement score weights (1.0 / 0.05)
    scale_high / scale_low             autoscale queue watermarks (8 / 0)

Observability: ``/serving{locality#L/fleet#i}/fleet/*`` counters
(placement hits by prefix vs load, digest staleness, autoscale
up/down, per-worker queue depth — ``cache/counters.register_fleet``)
and ``serving.fleet.place`` tracing spans whose flow arrows chain
placement into the admit→prefill→decode DAG.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..cache.radix import prefix_hashes
from ..cache.transfer import make_segment
from ..models.disagg import (DisaggRouter, InProcHandle, WorkerHandle,
                             _WorkerDown)
from ..synchronization import Mutex
from . import flight, tracing

__all__ = ["FleetRouter"]


class FleetRouter(DisaggRouter):
    """Prefix-cache-aware, autoscaling front end over the
    disaggregated topology. Construction, admission, failover, and
    the zero-leak close contract are all inherited from
    :class:`DisaggRouter`; this subclass swaps the placement policy,
    seeds prefills from placed workers' caches, and runs the
    autoscaler inside the step loop.

    Threading: the ``_fl_lock``-guarded counters (placement tallies,
    prefill-savings, autoscale/retire bookkeeping, the digest table)
    are mutated with the lock held at every site — hpxlint HPX019
    infers that guarded-by contract from the real mutation sites and
    the real-tree test pins it; per-placement loop state is
    deliberately bare (step-loop-local, never shared)."""

    def __init__(self, params, cfg,
                 prefill_workers: Optional[int] = None,
                 decode_workers: Optional[int] = None, *,
                 slots: int = 4, smax: int = 512, decode_mesh=None,
                 prefill_handles: Optional[List[WorkerHandle]] = None,
                 decode_handles: Optional[List[WorkerHandle]] = None,
                 decode_factory=None,
                 server_kwargs: Optional[dict] = None) -> None:
        from ..core.config import runtime_config
        rc = runtime_config()
        if prefill_workers is None:
            prefill_workers = rc.get_int(
                "hpx.serving.fleet.prefill_workers", 2)
        if decode_workers is None:
            decode_workers = rc.get_int(
                "hpx.serving.fleet.decode_workers", 2)
        placement = rc.get("hpx.serving.fleet.placement", "prefix")
        if placement not in ("prefix", "load"):
            raise ValueError(
                "hpx.serving.fleet.placement must be 'prefix' or "
                f"'load', got {placement!r}")
        self._placement = placement
        self._digest_entries = max(1, rc.get_int(
            "hpx.serving.fleet.digest_entries", 64))
        self._digest_refresh_s = rc.get_float(
            "hpx.serving.fleet.digest_refresh_s", 0.25)
        self._w_prefix = rc.get_float(
            "hpx.serving.fleet.w_prefix", 1.0)
        self._w_pressure = rc.get_float(
            "hpx.serving.fleet.w_pressure", 0.05)
        self._w_tier = rc.get_float(
            "hpx.serving.fleet.w_tier", 0.25)
        self._pool_min = max(1, rc.get_int(
            "hpx.serving.fleet.decode_pool_min", 1))
        self._pool_max = rc.get_int(
            "hpx.serving.fleet.decode_pool_max", 4)
        self._scale_high = max(1, rc.get_int(
            "hpx.serving.fleet.scale_high", 8))
        self._scale_low = max(0, rc.get_int(
            "hpx.serving.fleet.scale_low", 0))
        self._idle_ticks = max(1, rc.get_int(
            "hpx.serving.fleet.idle_ticks", 16))
        self._decode_factory = decode_factory
        # observability state: the counter callbacks
        # (cache/counters.register_fleet) read these from the sampler
        # thread, so the bookkeeping lock guards them. ORDER: this
        # lock nests INSIDE nothing and takes nothing under it —
        # worker calls (and thus allocator/radix locks) always happen
        # outside the critical section.
        self._fl_lock = Mutex()
        self._placed_prefix = 0
        self._placed_load = 0
        self._autoscale_up = 0
        self._autoscale_down = 0
        self._retired_leaked = 0
        self.prefill_tokens_saved = 0
        self._digests: Dict[int, Dict[str, Any]] = {}
        self._place_flows: Dict[int, int] = {}
        self._idle_streak: Dict[int, int] = {}
        super().__init__(params, cfg, prefill_workers, decode_workers,
                         slots=slots, smax=smax,
                         decode_mesh=decode_mesh,
                         prefill_handles=prefill_handles,
                         decode_handles=decode_handles,
                         server_kwargs=server_kwargs)
        self._pool_max = max(self._pool_max, len(self._decode))
        from ..cache.counters import register_fleet
        self.counter_instance = register_fleet(self)

    # -- digest cache ------------------------------------------------------

    def _digest(self, h: WorkerHandle) -> Dict[str, Any]:
        """The worker's prefix digest, refreshed when older than the
        freshness window. Eviction RATE (the cache-pressure feedback)
        is the delta between consecutive pulls over their spacing —
        a worker shedding chains fast scores down even when it still
        matches."""
        now = time.monotonic()
        with self._fl_lock:
            ent = self._digests.get(id(h))
        if ent is not None \
                and now - ent["at"] < self._digest_refresh_s:
            return ent
        d = self._call(h, "prefix_digest", self._digest_entries)
        rate = 0.0
        if ent is not None:
            dt = max(now - ent["at"], 1e-6)
            rate = max(0.0, (int(d["evictions"]) - ent["evictions"])
                       / dt)
        ent = {"set": frozenset(int(x) for x in d["hashes"]),
               # chains held only in the worker's host tier — cold but
               # restorable, scored with the discounted w_tier weight
               "tier_set": frozenset(
                   int(x) for x in d.get("tier_hashes", ())),
               "at": now, "evictions": int(d["evictions"]),
               "rate": rate}
        with self._fl_lock:
            self._digests[id(h)] = ent
        return ent

    def digest_staleness_s(self) -> float:
        """Age of the OLDEST cached digest — the /serving fleet
        counter's staleness gauge (0 before any pull)."""
        now = time.monotonic()
        with self._fl_lock:
            ages = [now - e["at"] for e in self._digests.values()]
        return max(ages) if ages else 0.0

    # -- placement ---------------------------------------------------------

    def _place_decode(self, req) -> WorkerHandle:
        cands = self._placeable_decode()
        with tracing.span("serving.fleet.place", "serving",
                          rid=req.rid, candidates=len(cands)):
            best, best_score, best_matched = None, 0.0, 0
            if self._placement == "prefix" and len(cands) > 1:
                hs = prefix_hashes(req.prompt[:-1], self._block_size)
                for h in cands:
                    ent = self._digest(h)
                    matched = 0
                    for i in range(len(hs) - 1, -1, -1):
                        if hs[i] in ent["set"]:
                            matched = i + 1
                            break
                    # tier depth: how far the worker covers the prompt
                    # counting its HOST tier too — blocks it holds only
                    # cold score at w_prefix * w_tier (restore beats a
                    # cold miss, recompute beats a restore), so a
                    # worker holding the prefix cold still outranks one
                    # without it
                    tiered = matched
                    for i in range(len(hs) - 1, matched - 1, -1):
                        if hs[i] in ent["tier_set"]:
                            tiered = i + 1
                            break
                    if not tiered:
                        continue
                    score = (matched * self._w_prefix
                             + (tiered - matched) * self._w_prefix
                             * self._w_tier
                             - ent["rate"] * self._w_pressure)
                    if score > best_score:
                        best, best_score = h, score
                        best_matched = tiered
            if best is None:
                best = self._least_loaded_decode()
            with self._fl_lock:
                if best_matched:
                    self._placed_prefix += 1
                else:
                    self._placed_load += 1
            self.timeline.event(
                req.grid, "fleet_place",
                by="prefix" if best_matched else "load",
                matched_blocks=best_matched,
                worker=self._decode.index(best))
            if tracing.active_tracer() is not None:
                tracing.instant(
                    "serving.fleet.placed", "serving", rid=req.rid,
                    by="prefix" if best_matched else "load",
                    matched_blocks=best_matched,
                    worker=self._decode.index(best))
                # flow tail anchors to the place slice; the head binds
                # inside the admit span, drawing the placement →
                # prefill-done → decode-admit arrow across steps
                self._place_flows[req.rid] = tracing.flow_begin(
                    "serving.fleet.place")
        return best

    def _admit_decode(self, req) -> None:
        fid = self._place_flows.pop(req.rid, None)
        with tracing.span("serving.fleet.admit", "serving",
                          rid=req.rid):
            tracing.flow_end(fid, "serving.fleet.place")
            super()._admit_decode(req)

    # -- prefix-seeded prefill dispatch ------------------------------------

    def _start_prefill_job(self, req, h: WorkerHandle) -> None:
        """Seed the prefill from the placed decode worker's cache,
        then open the job with the prefix rows — only the suffix
        recomputes. Every mutation of router state (segment
        retention) commits BEFORE the send it covers, so a death at
        any point re-dispatches cleanly:

        * fetch fails → nothing retained, request stays queued;
        * a ship fails → seeded segments are retained, the next
          dispatch re-ships them to the fresh placement (ingest
          dedups by seq, so a re-delivery to a surviving worker is
          harmless);
        * start fails → same, plus the prefill re-dispatches.
        """
        if not req.segments and self._placement == "prefix":
            self._seed_from_cache(req)
        elif req.segments:
            # re-dispatch after a loss mid-dispatch: the retained
            # segments re-ship to the (possibly re-placed) decode
            # worker before prefill reopens from them
            for seg in sorted(req.segments, key=lambda s: s.start):
                self._ship(req, seg)
        prefix = None
        if req.segments:
            segs = sorted(req.segments, key=lambda s: s.start)
            prefix = np.concatenate([s.payload for s in segs], axis=2)
        self._call(h, "start", req.grid, req.prompt,
                   req.temperature, req.key, prefix)

    def _seed_from_cache(self, req) -> None:
        out = self._call(req.decode_h, "fetch_prefix",
                         req.prompt[:-1])
        matched = int(out["matched"])
        if not matched:
            return
        rows = np.asarray(out["rows"])
        bs, plen = self._block_size, len(req.prompt)
        segs = [make_segment(req.grid, a // bs, a, plen,
                             rows[:, :, a:a + bs])
                for a in range(0, matched, bs)]
        req.segments.extend(segs)      # retain BEFORE shipping: a
        for seg in segs:               # failover re-ships exactly
            self._ship(req, seg)       # these
        with self._fl_lock:
            self.prefill_tokens_saved += matched

    # -- autoscaling -------------------------------------------------------

    def step(self) -> bool:
        if self._degraded:
            return self._local_step()
        try:
            self._autoscale()
            self._dispatch_prefills()
            self._advance_prefills()
            self._pump_decodes()
        except _WorkerDown as wd:
            self._on_worker_failure(wd.handle, wd.cause)
        return self._unfinished() > 0

    def _new_decode_handle(self) -> WorkerHandle:
        if self._decode_factory is not None:
            h = self._decode_factory()
        else:
            h = InProcHandle("decode", self._make_decode_worker(),
                             locality=len(self._decode))
        # autoscaled workers join the router-level tune arbiter like
        # the construction-time pool (DisaggRouter.__init__)
        from .autotune import attach_arbiter
        attach_arbiter(h, self._tune_arbiter,
                       f"decode#{len(self._decode)}")
        return h

    def _autoscale(self) -> None:
        """One scale decision per tick, queue-depth driven: mint a
        worker when the admission queue crests the high watermark,
        drain a PERSISTENTLY idle worker (``idle_ticks`` consecutive
        unassigned ticks — one empty tick between requests must not
        thrash a warm radix tree away) once the queue sits at the low
        watermark. A drain a cascade interrupted (the re-dispatch
        target died mid-retire) completes first — draining workers
        never take placements, so leaving one half-retired only
        wastes its slots."""
        for h in [w for w in self._decode if w.draining]:
            self._retire(h)
        depth = len(self._qi) + len(self._qb)
        placeable = [h for h in self._alive(self._decode)
                     if not h.draining]
        load = self._decode_load()
        for h in placeable:
            if load[id(h)] == 0:
                self._idle_streak[id(h)] = \
                    self._idle_streak.get(id(h), 0) + 1
            else:
                self._idle_streak[id(h)] = 0
        if depth >= self._scale_high \
                and len(placeable) < self._pool_max:
            h = self._new_decode_handle()
            self._decode.append(h)
            with self._fl_lock:
                self._autoscale_up += 1
            tracing.instant("serving.fleet.scale_up", "serving",
                            queue=depth, pool=len(self._decode))
        elif depth <= self._scale_low \
                and len(placeable) > self._pool_min:
            idle = [h for h in placeable
                    if self._idle_streak.get(id(h), 0)
                    >= self._idle_ticks]
            if idle:
                # retire the newest idle worker: index-0 workers keep
                # their warm radix trees (placement value) longest
                h = max(idle, key=lambda w: self._decode.index(w))
                h.draining = True
                self._idle_streak.pop(id(h), None)
                tracing.instant("serving.fleet.scale_down", "serving",
                                queue=depth,
                                worker=self._decode.index(h))
                self._retire(h)

    def _retire(self, h: WorkerHandle) -> None:
        """Finish a drain: re-dispatch everything `h` still owns
        (``_failover_decode`` commits ``req.decode_h`` to the target
        BEFORE the risky re-ship/re-admit — the every-cross-worker-
        call-site rule), close the worker, and fold its post-eviction
        block count into the router's leak accounting so scale-down
        can never hide a leak."""
        others = [w for w in self._alive(self._decode)
                  if w is not h and not w.draining]
        if not others:
            h.draining = False      # nowhere to hand off: drain aborts
            return
        flight.record_fault("autoscale-drain", site="fleet",
                            timeline=self.timeline)
        if h.alive:
            affected = sorted(
                (r for r in self._reqs.values()
                 if r.state in ("prefill", "decode")
                 and r.decode_h is h),
                key=lambda r: r.rid)
            for req in affected:
                self._failover_decode(req)
        leaked = 0
        if h.alive:
            try:
                self._call(h, "close", False)
                leaked = int(self._call(h, "leaked_blocks"))
            except _WorkerDown:
                leaked = 0          # died mid-retire: it owned nothing
        self._decode.remove(h)
        self._idle_streak.pop(id(h), None)
        with self._fl_lock:
            self._retired_leaked += leaked
            self._autoscale_down += 1
            self._digests.pop(id(h), None)

    # -- observability -----------------------------------------------------

    def worker_queue_depth(self, k: int) -> int:
        """In-flight requests on decode worker index `k` (0 for an
        index past the current pool — per-worker counters register up
        to the autoscale ceiling)."""
        if k >= len(self._decode):
            return 0
        return self._decode_load()[id(self._decode[k])]

    def leaked_blocks(self) -> int:
        """Base accounting (surviving workers + colocated fallback)
        PLUS everything scale-down retirement measured — workers
        leaving the pool take their leaks into the ledger, not out of
        it."""
        return super().leaked_blocks() + self._retired_leaked

    def stats(self) -> Dict[str, Any]:
        st = super().stats()
        with self._fl_lock:
            st.update({
                "placed_prefix": self._placed_prefix,
                "placed_load": self._placed_load,
                "autoscale_up": self._autoscale_up,
                "autoscale_down": self._autoscale_down,
                "retired_leaked": self._retired_leaked,
                "prefill_tokens_saved": self.prefill_tokens_saved,
            })
        st["decode_pool"] = len(self._alive(self._decode))
        st["digest_staleness_s"] = self.digest_staleness_s()
        return st

    def _statusz(self) -> Dict[str, Any]:
        """Fleet view on the router's /statusz section: the base
        census plus the autoscale pool bounds and per-worker queue
        depth — the merged fleet picture one ops-plane port serves."""
        doc = super()._statusz()
        doc["kind"] = "fleet"
        doc["pool"] = {
            "min": self._pool_min, "max": self._pool_max,
            "decode": len(self._decode),
            "alive": len(self._alive(self._decode)),
            "scale_high": self._scale_high,
            "scale_low": self._scale_low,
        }
        doc["worker_queue_depth"] = {
            str(k): self.worker_queue_depth(k)
            for k in range(len(self._decode))}
        return doc
