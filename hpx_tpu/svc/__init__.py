"""Services layer (SURVEY.md §2.5): performance counters, checkpoint,
resiliency, logging, distributed iostreams, profiler bridge."""
