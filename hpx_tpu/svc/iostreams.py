"""Distributed console output — hpx::cout (SURVEY.md §2.5 'iostreams').

Reference analog: components/iostreams — output written to hpx::cout on
ANY locality is marshalled to the console locality (0) and printed
there, so multi-process runs produce one coherent stream instead of N
interleaved stdouts.

Usage:
    from hpx_tpu.svc.iostreams import cout, cerr
    cout.println(f"locality {hpx.find_here()} ready")
    cout.write("partial "); cout.write("line\\n"); cout.flush()
"""

from __future__ import annotations

import sys
from typing import Any, List

from ..dist.actions import async_action, plain_action
from ..dist.runtime import find_here, find_root_locality
from ..futures.future import Future
from ..synchronization import Mutex


@plain_action(name="iostreams.write")
def _console_write(stream: str, text: str) -> bool:
    out = sys.stderr if stream == "cerr" else sys.stdout
    out.write(text)
    out.flush()
    return True


class _DistStream:
    """Buffers locally per line; ships to the console locality on flush
    (and on newline, matching hpx::endl / hpx::flush behavior)."""

    def __init__(self, stream: str) -> None:
        self._stream = stream
        self._buf: List[str] = []
        self._pending: List[Future] = []
        self._lock = Mutex()

    def write(self, text: Any) -> "_DistStream":
        s = str(text)
        with self._lock:
            self._buf.append(s)
        if "\n" in s:
            self.flush()
        return self

    def println(self, text: Any = "") -> "_DistStream":
        return self.write(f"{text}\n")

    # operator<< spelling for easy porting from the reference API
    __lshift__ = write

    def flush(self) -> Future:
        """Returns a future that completes once everything written so far
        (including writes shipped by earlier newline-triggered flushes
        still in flight) has been printed on the console locality; remote
        write failures propagate through .get()."""
        from ..futures.combinators import when_all
        from ..futures.future import make_ready_future

        with self._lock:
            text = "".join(self._buf)
            self._buf.clear()
            pending = list(self._pending)
        if text:
            root = find_root_locality()
            if find_here() == root:
                _console_write.fn(self._stream, text)
            else:
                f = async_action(_console_write, root, self._stream, text)
                with self._lock:
                    self._pending.append(f)
                pending.append(f)
        if not pending:
            return make_ready_future(True)

        def settle(ready: Future) -> bool:
            with self._lock:
                self._pending = [p for p in self._pending
                                 if p not in pending]
            for f in ready.get():
                f.get()          # propagate any remote-write exception
            return True

        return when_all(pending).then(settle)


cout = _DistStream("cout")
cerr = _DistStream("cerr")
