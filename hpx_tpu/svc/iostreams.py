"""Distributed console output — hpx::cout (SURVEY.md §2.5 'iostreams').

Reference analog: components/iostreams — output written to hpx::cout on
ANY locality is marshalled to the console locality (0) and printed
there, so multi-process runs produce one coherent stream instead of N
interleaved stdouts.

Usage:
    from hpx_tpu.svc.iostreams import cout, cerr
    cout.println(f"locality {hpx.find_here()} ready")
    cout.write("partial "); cout.write("line\\n"); cout.flush()
"""

from __future__ import annotations

import sys
import threading
from typing import Any, List

from ..dist.actions import async_action, plain_action
from ..dist.runtime import find_here, find_root_locality
from ..futures.future import Future


@plain_action(name="iostreams.write")
def _console_write(stream: str, text: str) -> bool:
    out = sys.stderr if stream == "cerr" else sys.stdout
    out.write(text)
    out.flush()
    return True


class _DistStream:
    """Buffers locally per line; ships to the console locality on flush
    (and on newline, matching hpx::endl / hpx::flush behavior)."""

    def __init__(self, stream: str) -> None:
        self._stream = stream
        self._buf: List[str] = []
        self._lock = threading.Lock()

    def write(self, text: Any) -> "_DistStream":
        s = str(text)
        with self._lock:
            self._buf.append(s)
        if "\n" in s:
            self.flush()
        return self

    def println(self, text: Any = "") -> "_DistStream":
        return self.write(f"{text}\n")

    # operator<< spelling for easy porting from the reference API
    __lshift__ = write

    def flush(self) -> Future:
        with self._lock:
            text = "".join(self._buf)
            self._buf.clear()
        if not text:
            from ..futures.future import make_ready_future
            return make_ready_future(True)
        root = find_root_locality()
        if find_here() == root:
            _console_write.fn(self._stream, text)
            from ..futures.future import make_ready_future
            return make_ready_future(True)
        # async ship to console; returned future completes when printed
        return async_action(_console_write, root, self._stream, text)


cout = _DistStream("cout")
cerr = _DistStream("cerr")
