"""Per-program continuous profiler over the jit-program cache.

Reference analog: APEX's per-task timers plus HPX's roofline-style
counters — the PAPERS.md adaptive-executor line ("A New Execution Model
and Executor for Adaptively Optimizing ... Using HPX") needs per-program
achieved-vs-peak data before any policy can act on it.

Every module that memoizes compiled programs funnels through
``core.programs.cached_program``; this module installs a build-time
hook there so each cache MISS is timed (compile wall time) and the
stored program is replaced by a thin callable proxy that records
per-call execute wall time into a :class:`metrics.HistogramCounter`.
Cache HITS return the stored proxy — the hot path pays one
``perf_counter`` pair per call and nothing else.  When XLA cost
analysis is available the first call additionally captures FLOPs and
bytes-accessed per call, yielding achieved GFLOP/s and a roofline
fraction against ``hpx.prof.peak_gflops`` (0 = infer from the device
kind; unknown kinds report 0).

Exposure planes:

* ``/programs{locality#N/<tag>#i}/...`` performance counters —
  ``time/execute-s`` (histogram + derived pNN quantiles),
  ``count/calls``, ``time/compile-s``, ``gflops/achieved``,
  ``roofline/fraction`` — so Prometheus rows and Perfetto counter
  tracks (``hpx.trace.counters`` samples ``/programs*`` by default)
  come for free from the existing exposition paths.
* :func:`profile_table` — a JSON-safe fold serving_bench embeds in the
  ``--metrics-out`` artifact and the flight recorder persists in every
  bundle.
* an HBM/host high-water-mark sampler (:class:`MemoryWatermark`)
  riding ``profiling.device_memory_stats``.

Lifecycle mirrors tracing: :func:`start_profiling` /
:func:`stop_profiling` / :func:`active_profiler`, with
:func:`start_if_configured` gated on ``hpx.prof.programs``.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..core import programs as _programs
from ..synchronization import Mutex
from . import performance_counters as pc
from . import profiling as _profiling
from .metrics import HistogramCounter, register_histogram

__all__ = [
    "PROFILE_SCHEMA",
    "ProgramProfiler",
    "MemoryWatermark",
    "start_profiling",
    "stop_profiling",
    "active_profiler",
    "start_if_configured",
    "profile_table",
]

PROFILE_SCHEMA = "hpx_tpu.progprof.v1"


def _cfg():
    from ..core.config import runtime_config
    return runtime_config()


# rough bf16 peak GFLOP/s per device kind, the roofline denominator
# when hpx.prof.peak_gflops is 0 (case-insensitive substring match on
# jax's device_kind; CPU and unknown kinds fall through to 0 = unknown)
_DEVICE_PEAK_GFLOPS: Tuple[Tuple[str, float], ...] = (
    ("v6e", 918_000.0),
    ("v5p", 459_000.0),
    ("v5e", 197_000.0),
    ("v5 lite", 197_000.0),
    ("v4", 275_000.0),
    ("v3", 123_000.0),
    ("v2", 45_000.0),
)


def _host_rss_bytes() -> int:
    try:
        import os
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except Exception:  # noqa: BLE001 — non-procfs platforms report 0
        return 0


def _key_label(key: Any) -> str:
    """Compact, stable label for a program-cache key: the leading str
    tag every cache in the tree uses (("decode", cfg, ...) → "decode"),
    sanitized to counter-instance charset."""
    if isinstance(key, tuple) and key and isinstance(key[0], str):
        raw = key[0]
    elif isinstance(key, str):
        raw = key
    else:
        raw = type(key).__name__
    out = "".join(ch if ch.isalnum() or ch in "-_." else "-"
                  for ch in raw)
    return out or "prog"


class ProgramRecord:
    """Accounting for ONE cached program key."""

    __slots__ = ("key", "label", "instance", "compiles", "compile_s",
                 "exec_hist", "flops", "bytes_accessed", "cost_pending",
                 "counter_names")

    def __init__(self, key: Any, label: str, instance: str,
                 cost_pending: bool) -> None:
        self.key = key
        self.label = label
        self.instance = instance
        self.compiles = 0
        self.compile_s = 0.0
        self.exec_hist = HistogramCounter()
        self.flops: Optional[float] = None          # per call
        self.bytes_accessed: Optional[float] = None  # per call
        self.cost_pending = cost_pending
        self.counter_names: List[str] = []

    @property
    def calls(self) -> int:
        return self.exec_hist.count

    def achieved_gflops(self) -> float:
        """FLOPs/call over mean execute seconds, in GFLOP/s (0 when
        cost analysis is unavailable or nothing ran)."""
        mean = self.exec_hist.mean()
        if self.flops is None or mean <= 0.0:
            return 0.0
        return self.flops / mean / 1e9

    def roofline_fraction(self, peak_gflops: float) -> float:
        if peak_gflops <= 0.0:
            return 0.0
        return self.achieved_gflops() / peak_gflops


class _ProfiledProgram:
    """Callable proxy stored in the program cache in place of the jit
    program: times each call into the record's histogram; everything
    else (``lower``, ``clear_cache``, ...) passes through."""

    __slots__ = ("_prog", "_rec", "_prof")

    def __init__(self, prog: Callable, rec: ProgramRecord,
                 prof: "ProgramProfiler") -> None:
        self._prog = prog
        self._rec = rec
        self._prof = prof

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        rec = self._rec
        if rec.cost_pending:
            self._prof._cost_analyze(rec, self._prog, args, kwargs)
        t0 = time.perf_counter()
        out = self._prog(*args, **kwargs)
        rec.exec_hist.record(time.perf_counter() - t0)
        return out

    def __getattr__(self, name: str) -> Any:
        return getattr(self._prog, name)

    def __repr__(self) -> str:
        return f"_ProfiledProgram({self._rec.label!r})"


class MemoryWatermark:
    """HBM/host RSS high-water-mark sampler.  ``sample()`` is direct
    (tests call it synchronously); ``start()`` spins the periodic
    daemon thread.  Device peak comes from
    ``profiling.device_memory_stats`` (`peak_bytes_in_use`, falling
    back to `bytes_in_use` on backends without peak tracking)."""

    def __init__(self, interval_s: float = 0.05) -> None:
        self.interval_s = max(0.001, float(interval_s))
        self.hbm_peak_bytes = 0
        self.host_peak_bytes = 0
        self.samples = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def sample(self) -> None:
        st = _profiling.device_memory_stats()
        peak = st.get("peak_bytes_in_use", st.get("bytes_in_use", 0)) or 0
        if peak > self.hbm_peak_bytes:
            self.hbm_peak_bytes = int(peak)
        rss = _host_rss_bytes()
        if rss > self.host_peak_bytes:
            self.host_peak_bytes = rss
        self.samples += 1

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop() -> None:
            while not self._stop.wait(self.interval_s):
                self.sample()

        self._thread = threading.Thread(
            target=loop, daemon=True, name="hpx-progprof-mem")
        self._thread.start()

    def stop(self) -> None:
        t = self._thread
        if t is None:
            return
        self._stop.set()
        t.join(timeout=2.0)
        self._thread = None

    def snapshot(self) -> Dict[str, int]:
        return {"hbm_peak_bytes": self.hbm_peak_bytes,
                "host_peak_bytes": self.host_peak_bytes,
                "samples": self.samples}


class ProgramProfiler:
    """Owns the program records, their registered counters, and the
    memory watermark.  Install via :func:`start_profiling` (or
    construct + ``install()`` directly in tests)."""

    def __init__(self, sample_memory: bool = True,
                 mem_interval_s: float = 0.05) -> None:
        cfg = _cfg()
        self._lock = Mutex()
        self._records: Dict[Any, ProgramRecord] = {}
        self._names: List[str] = []
        self._cost_enabled = cfg.get_bool("hpx.prof.cost_analysis", True)
        self.peak_gflops = self._resolve_peak()
        self.cost_failures = 0
        self._sample_memory = sample_memory
        self.memory = MemoryWatermark(mem_interval_s)
        self._installed = False

    @staticmethod
    def _resolve_peak() -> float:
        v = _cfg().get_float("hpx.prof.peak_gflops", 0.0)
        if v > 0.0:
            return v
        try:
            import jax
            kind = jax.devices()[0].device_kind.lower()
        except Exception:  # noqa: BLE001
            return 0.0
        for frag, peak in _DEVICE_PEAK_GFLOPS:
            if frag in kind:
                return peak
        return 0.0

    # -- the cached_program build hook --------------------------------

    def _build_hook(self, key: Any, build: Callable[[], Any]) -> Any:
        t0 = time.perf_counter()
        prog = build()
        dt = time.perf_counter() - t0
        if not callable(prog):
            return prog     # plans/tuples: nothing to time per-call
        rec = self._record_for(key)
        rec.compiles += 1
        rec.compile_s += dt
        return _ProfiledProgram(prog, rec, self)

    def _record_for(self, key: Any) -> ProgramRecord:
        with self._lock:
            rec = self._records.get(key)
            if rec is None:
                label = _key_label(key)
                instance = f"{label}#{len(self._records)}"
                rec = ProgramRecord(key, label, instance,
                                    cost_pending=self._cost_enabled)
                self._records[key] = rec
                self._register_record(rec)
            return rec

    def _register_record(self, rec: ProgramRecord) -> None:
        names = register_histogram("programs", "time/execute-s",
                                   rec.exec_hist, rec.instance)

        def put(counter: str, fn: Callable[[], float]) -> None:
            name = pc.counter_name("programs", counter, rec.instance)
            pc.register_counter(name, pc.CallbackCounter(fn))
            names.append(name)

        put("count/calls", lambda r=rec: float(r.calls))
        put("time/compile-s", lambda r=rec: r.compile_s)
        put("gflops/achieved", lambda r=rec: r.achieved_gflops())
        put("roofline/fraction",
            lambda r=rec, p=self: r.roofline_fraction(p.peak_gflops))
        rec.counter_names = names
        self._names.extend(names)

    def _cost_analyze(self, rec: ProgramRecord, prog: Callable,
                      args: tuple, kwargs: dict) -> None:
        """First-call FLOPs/bytes capture: lower with the concrete
        call's args (tracing only — donated buffers are untouched) and
        read XLA cost analysis.  Failures are expected off-TPU; they
        count on ``cost_failures`` and never reach the caller."""
        rec.cost_pending = False
        try:
            lower = getattr(prog, "lower", None)
            if lower is None:
                return
            lowered = lower(*args, **kwargs)
            try:
                ca = lowered.cost_analysis()
            except Exception:  # noqa: BLE001 — platform-dependent API
                ca = lowered.compile().cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            if not isinstance(ca, dict):
                return
            flops = ca.get("flops")
            nbytes = ca.get("bytes accessed")
            rec.flops = float(flops) if flops is not None else None
            rec.bytes_accessed = \
                float(nbytes) if nbytes is not None else None
        except Exception:  # noqa: BLE001 — profiler must not break serving
            self.cost_failures += 1

    # -- lifecycle ----------------------------------------------------

    def install(self) -> None:
        _programs.set_profile_hook(self._build_hook)
        self._installed = True
        if self._sample_memory:
            self.memory.start()
        with self._lock:
            if not any(n.endswith("memory/hbm-peak-bytes")
                       for n in self._names):
                for counter, fn in (
                        ("memory/hbm-peak-bytes",
                         lambda: float(self.memory.hbm_peak_bytes)),
                        ("memory/host-peak-bytes",
                         lambda: float(self.memory.host_peak_bytes))):
                    name = pc.counter_name("programs", counter)
                    pc.register_counter(name, pc.CallbackCounter(fn))
                    self._names.append(name)

    def close(self) -> None:
        if _programs.profile_hook() == self._build_hook:
            _programs.set_profile_hook(None)
        self._installed = False
        self.memory.stop()
        with self._lock:
            names, self._names = self._names, []
        for name in names:
            pc.unregister_counter(name)

    # -- reading ------------------------------------------------------

    def records(self) -> List[ProgramRecord]:
        with self._lock:
            return list(self._records.values())

    def profile_table(self) -> Dict[str, Any]:
        """JSON-safe fold of every record, busiest (total execute
        seconds) first — the section serving_bench embeds under
        ``"programs"`` in the metrics artifact and the flight recorder
        persists per bundle."""
        rows: List[Dict[str, Any]] = []
        for rec in sorted(self.records(),
                          key=lambda r: -r.exec_hist.sum):
            h = rec.exec_hist
            rows.append({
                "key": rec.label,
                "instance": rec.instance,
                "compiles": rec.compiles,
                "compile_s": rec.compile_s,
                "calls": h.count,
                "total_s": h.sum,
                "mean_s": h.mean(),
                "p50_s": h.quantile(0.5),
                "p99_s": h.quantile(0.99),
                "relative_error_bound": h.relative_error_bound(),
                "flops_per_call": rec.flops,
                "bytes_per_call": rec.bytes_accessed,
                "achieved_gflops": rec.achieved_gflops(),
                "roofline_fraction":
                    rec.roofline_fraction(self.peak_gflops),
            })
        return {
            "schema": PROFILE_SCHEMA,
            "peak_gflops": self.peak_gflops,
            "cost_failures": self.cost_failures,
            "memory": self.memory.snapshot(),
            "programs": rows,
        }


# ---------------------------------------------------------------------------
# module lifecycle (tracing-style singleton)
# ---------------------------------------------------------------------------

_active: Optional[ProgramProfiler] = None


def start_profiling(sample_memory: bool = True,
                    mem_interval_s: float = 0.05) -> ProgramProfiler:
    """Create, install and return the process program profiler.
    Raises if one is active."""
    global _active
    if _active is not None:
        raise RuntimeError(
            "program profiler already active; stop_profiling() first")
    prof = ProgramProfiler(sample_memory=sample_memory,
                           mem_interval_s=mem_interval_s)
    _active = prof
    prof.install()
    return prof


def stop_profiling() -> Optional[ProgramProfiler]:
    """Stop and detach the active profiler (returned so callers can
    still fold its table into artifacts).  With ``hpx.perfdb.record=1``
    and a store configured, the table is also banked into the perfdb
    observation log (per-program compile/execute costs, provenance-
    stamped) — the live producer half of the offline ladder loop."""
    global _active
    prof = _active
    _active = None
    if prof is not None:
        prof.close()
        _bank_to_perfdb(prof)
    return prof


def _bank_to_perfdb(prof: ProgramProfiler) -> None:
    from . import perfdb
    if not perfdb.record_enabled():
        return
    db = perfdb.configured_db()
    if db is None:
        return
    if perfdb.bank_profile(db, prof.profile_table(),
                           perfdb.live_key()):
        db.save()


def active_profiler() -> Optional[ProgramProfiler]:
    return _active


def start_if_configured() -> Optional[ProgramProfiler]:
    """Start profiling iff ``hpx.prof.programs`` is truthy and no
    profiler is active — the config-gated entry point bench harnesses
    use."""
    if _active is not None:
        return _active
    if not _cfg().get_bool("hpx.prof.programs", False):
        return None
    return start_profiling()


def profile_table() -> Optional[Dict[str, Any]]:
    """The active profiler's table, or None when profiling is off —
    flight bundles and metrics artifacts embed this verbatim."""
    prof = _active
    return prof.profile_table() if prof is not None else None
