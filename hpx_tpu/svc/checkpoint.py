"""Checkpoint/restore — coarse-grained recovery (SURVEY.md §5.4).

Reference analog: libs/full/checkpoint (+checkpoint_base):
`save_checkpoint(args...) -> future<checkpoint>` serializes an argument
pack with the parcel serializer (anything action-serializable
checkpoints for free, futures contribute their values);
`restore_checkpoint(cp, args&...)`; checkpoints stream to/from files.

TPU-first: device arrays are pulled to host per-shard through the parcel
serializer's jax encoding; PartitionedVector checkpoints carry layout
metadata (partition count + mesh axis) and are re-placed onto the
CURRENT process's mesh on restore — a checkpoint written on an 8-chip
mesh restores onto whatever mesh the restoring run has, which is the
useful elasticity story for device counts that changed between runs.
"""

from __future__ import annotations

import io
import os
from typing import Any, BinaryIO, List, Tuple, Union

from ..dist.serialization import deserialize, serialize
from ..futures.async_ import async_
from ..futures.future import Future, is_future

_MAGIC = b"HPXTPUCKPT1\n"


class _PVMarker:
    """PartitionedVector wire form: host data + layout metadata."""

    __slots__ = ("np_value", "num_partitions", "axis")

    def __init__(self, np_value, num_partitions: int, axis: str) -> None:
        self.np_value = np_value
        self.num_partitions = num_partitions
        self.axis = axis

    def restore(self):
        from ..containers import PartitionedVector
        from ..dist.distribution_policies import container_layout
        layout = container_layout(self.num_partitions, axis=self.axis)
        return PartitionedVector.from_array(self.np_value, layout)


def _encode(obj: Any) -> Any:
    """Resolve futures to their values; lower PartitionedVectors."""
    import numpy as np
    from ..containers import PartitionedVector
    if is_future(obj):
        return _encode(obj.get())
    if isinstance(obj, PartitionedVector):
        return _PVMarker(np.asarray(obj.to_numpy()),
                         obj.num_partitions, obj.layout.axis)
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        vals = [_encode(x) for x in obj]
        return t(vals) if t in (list, tuple) else vals
    if isinstance(obj, dict):
        return {k: _encode(v) for k, v in obj.items()}
    return obj


def _decode(obj: Any, sharded_ok: bool = False) -> Any:
    if isinstance(obj, _PVMarker):
        return obj.restore()
    if isinstance(obj, _ShardedMarker):
        if not sharded_ok:
            # a sharded-state file read through the PLAIN restore API
            # must fail loudly, not leak private marker objects
            raise ValueError(
                "checkpoint holds mesh-sharded leaves; restore it with "
                "restore_sharded_state(_from_file)(..., mesh=...)")
        return obj
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        vals = [_decode(x, sharded_ok) for x in obj]
        return t(vals) if t in (list, tuple) else vals
    if isinstance(obj, dict):
        return {k: _decode(v, sharded_ok) for k, v in obj.items()}
    return obj


class Checkpoint:
    """An opaque serialized argument pack (hpx::util::checkpoint)."""

    def __init__(self, data: bytes = b"") -> None:
        self.data = data

    def __len__(self) -> int:
        return len(self.data)

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, Checkpoint) and self.data == other.data

    # -- streaming (operator<< / operator>> analogs) ------------------------
    def write(self, stream: BinaryIO) -> None:
        stream.write(_MAGIC)
        stream.write(len(self.data).to_bytes(8, "little"))
        stream.write(self.data)

    @classmethod
    def read(cls, stream: BinaryIO) -> "Checkpoint":
        magic = stream.read(len(_MAGIC))
        if magic != _MAGIC:
            raise ValueError("not a hpx_tpu checkpoint stream")
        raw = stream.read(8)
        if len(raw) != 8:
            raise ValueError("truncated checkpoint stream (length header)")
        n = int.from_bytes(raw, "little")
        data = stream.read(n)
        if len(data) != n:
            raise ValueError("truncated checkpoint stream")
        return cls(data)


def save_checkpoint(*args: Any) -> Future:
    """Serialize the argument pack (futures are awaited, their VALUES are
    stored). Returns future<Checkpoint> — serialization runs as a task."""

    def build() -> Checkpoint:
        return Checkpoint(serialize(_encode(list(args))))

    return async_(build)


def save_checkpoint_sync(*args: Any) -> Checkpoint:
    return save_checkpoint(*args).get()


def restore_checkpoint(cp: Checkpoint, _sharded_ok: bool = False) -> Tuple:
    """Returns the restored argument pack as a tuple (Python can't fill
    out-params; a 1-arg checkpoint restores as a 1-tuple)."""
    return tuple(_decode(deserialize(cp.data), _sharded_ok))


def _publish(path: Union[str, os.PathLike], cp: Checkpoint) -> Checkpoint:
    """Write-then-atomic-rename: a kill mid-write can never truncate a
    previous good checkpoint at `path`."""
    import tempfile
    d = os.path.dirname(os.path.abspath(path)) or "."
    # unique temp per call: concurrent saves to one path must not
    # interleave into the same tmp file before the atomic publish
    fd, tmp = tempfile.mkstemp(dir=d, prefix=os.path.basename(
        str(path)) + ".tmp.")
    try:
        with os.fdopen(fd, "wb") as f:
            cp.write(f)
        os.replace(tmp, path)    # atomic publish
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return cp


def _save_to_file(path: Union[str, os.PathLike], build) -> Future:
    # serialize on the compute pool (CPU-bound), write on the "io"
    # helper pool (blocking syscalls off the scheduler workers — the
    # reference's io_service_pool split, SURVEY.md §2.1)
    from ..runtime.io_service import get_io_service_pool

    return async_(build).then(
        lambda fut: get_io_service_pool("io").async_execute(
            _publish, path, fut.get()))


def checkpoint_dir() -> str:
    """Base directory for named checkpoints — the hpx.checkpoint.dir
    knob (created on first use)."""
    from ..core.config import runtime_config
    d = runtime_config().get("hpx.checkpoint.dir") or "./checkpoints"
    os.makedirs(d, exist_ok=True)
    return d


def checkpoint_path(name: str) -> str:
    """Resolve a bare checkpoint name against hpx.checkpoint.dir;
    absolute paths and explicit relative paths pass through unchanged,
    so existing full-path callers keep their layout."""
    if os.path.isabs(name) or os.sep in name:
        return name
    return os.path.join(checkpoint_dir(), name)


def save_checkpoint_to_file(path: Union[str, os.PathLike],
                            *args: Any) -> Future:
    def build() -> Checkpoint:
        return Checkpoint(serialize(_encode(list(args))))

    return _save_to_file(path, build)


def restore_checkpoint_from_file(path: Union[str, os.PathLike]) -> Tuple:
    with open(path, "rb") as f:
        return restore_checkpoint(Checkpoint.read(f))


# ---------------------------------------------------------------------------
# Sharded train-state checkpointing (the TPU-native elasticity story)
# ---------------------------------------------------------------------------

class _ShardedMarker:
    """Wire form of a mesh-sharded jax.Array: host data + the
    PartitionSpec entries (as plain nested tuples), so restore can
    re-place the leaf onto the RESTORING run's mesh — same axis names,
    any device count (reference analog: the checkpoint restarting on a
    different locality count, SURVEY.md §5.4)."""

    __slots__ = ("np_value", "spec")

    def __init__(self, np_value, spec) -> None:
        self.np_value = np_value
        self.spec = spec


def _spec_entries(spec) -> tuple:
    out = []
    for e in spec:
        out.append(tuple(e) if isinstance(e, (tuple, list)) else e)
    return tuple(out)


def _sharded_payload(tree: Any) -> dict:
    """Flatten the pytree and lower mesh-sharded leaves to markers.
    The device→host pulls (np.asarray) happen HERE — EAGERLY on the
    caller, by design: a training loop with donated buffers
    (jit(donate_argnums=...)) invalidates the old state the moment the
    next step runs, so a deferred pull would race and read deleted
    arrays (the same class of bug hpxlint HPX020 catches statically
    inside one function). The snapshot is synchronous; serialization
    still runs as a task."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding

    leaves, treedef = jax.tree_util.tree_flatten(tree)

    def enc(leaf):
        if isinstance(leaf, jax.Array) and \
                isinstance(getattr(leaf, "sharding", None), NamedSharding):
            return _ShardedMarker(np.asarray(leaf),
                                  _spec_entries(leaf.sharding.spec))
        return leaf

    return {"treedef": treedef, "leaves": [enc(x) for x in leaves]}


def _sharded_build(tree: Any):
    """ONE build closure for both save paths (in-memory and file): the
    wire format cannot diverge between them. The payload (device→host
    snapshot) is taken eagerly — see _sharded_payload — and the closure
    serializes it as a task."""
    payload = _sharded_payload(tree)
    return lambda: Checkpoint(serialize(_encode([payload])))


def save_sharded_state(tree: Any) -> Future:
    """-> future<Checkpoint> of a PYTREE of jax arrays (a train state:
    params/opt state/step...). Mesh-sharded leaves record their
    PartitionSpec; restore_sharded_state re-places them on a given
    mesh. Unsharded leaves (host scalars, numpy, single-device arrays)
    ride the plain checkpoint path. The device→host snapshot is taken
    before this returns (donation-safe); serialization runs as a task."""
    return async_(_sharded_build(tree))


def save_sharded_state_to_file(path: Union[str, os.PathLike],
                               tree: Any) -> Future:
    """Same atomic tmp+rename publish and io-pool write as
    save_checkpoint_to_file — a kill mid-save never clobbers the
    previous good checkpoint."""
    return _save_to_file(path, _sharded_build(tree))


def restore_sharded_state(cp: Checkpoint, mesh=None) -> Any:
    """Rebuild the pytree; mesh-sharded leaves are device_put with
    their saved PartitionSpec over `mesh` (required when the checkpoint
    holds sharded leaves — the restoring mesh must use the same axis
    NAMES, the device count is free to differ as long as the saved
    global shapes still divide)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    restored = restore_checkpoint(cp, _sharded_ok=True)
    payload = restored[0] if len(restored) == 1 else None
    if not (isinstance(payload, dict)
            and {"treedef", "leaves"} <= payload.keys()):
        # friendly in BOTH directions of API mix-up (the reverse case
        # raises from _decode with a pointer to restore_sharded_state)
        raise ValueError(
            "not a sharded-state checkpoint; restore it with "
            "restore_checkpoint(_from_file)")
    leaves = []
    for leaf in payload["leaves"]:
        if isinstance(leaf, _ShardedMarker):
            if mesh is None:
                raise ValueError(
                    "restore_sharded_state: checkpoint holds sharded "
                    "leaves; pass mesh=")
            sh = NamedSharding(mesh, PartitionSpec(*leaf.spec))
            # device_put takes host memory straight to the SHARDED
            # layout; a jnp.asarray first would materialize the full
            # global array on device 0 (OOM for states that only fit
            # sharded — the exact elasticity use case)
            leaves.append(jax.device_put(leaf.np_value, sh))
        else:
            leaves.append(leaf)
    return jax.tree_util.tree_unflatten(payload["treedef"], leaves)


def restore_sharded_state_from_file(path: Union[str, os.PathLike],
                                    mesh=None) -> Any:
    with open(path, "rb") as stream:
        cp = Checkpoint.read(stream)
    return restore_sharded_state(cp, mesh=mesh)
