"""Checkpoint/restore — coarse-grained recovery (SURVEY.md §5.4).

Reference analog: libs/full/checkpoint (+checkpoint_base):
`save_checkpoint(args...) -> future<checkpoint>` serializes an argument
pack with the parcel serializer (anything action-serializable
checkpoints for free, futures contribute their values);
`restore_checkpoint(cp, args&...)`; checkpoints stream to/from files.

TPU-first: device arrays are pulled to host per-shard through the parcel
serializer's jax encoding; PartitionedVector checkpoints carry layout
metadata (partition count + mesh axis) and are re-placed onto the
CURRENT process's mesh on restore — a checkpoint written on an 8-chip
mesh restores onto whatever mesh the restoring run has, which is the
useful elasticity story for device counts that changed between runs.
"""

from __future__ import annotations

import io
import os
from typing import Any, BinaryIO, List, Tuple, Union

from ..dist.serialization import deserialize, serialize
from ..futures.async_ import async_
from ..futures.future import Future, is_future

_MAGIC = b"HPXTPUCKPT1\n"


class _PVMarker:
    """PartitionedVector wire form: host data + layout metadata."""

    __slots__ = ("np_value", "num_partitions", "axis")

    def __init__(self, np_value, num_partitions: int, axis: str) -> None:
        self.np_value = np_value
        self.num_partitions = num_partitions
        self.axis = axis

    def restore(self):
        from ..containers import PartitionedVector
        from ..dist.distribution_policies import container_layout
        layout = container_layout(self.num_partitions, axis=self.axis)
        return PartitionedVector.from_array(self.np_value, layout)


def _encode(obj: Any) -> Any:
    """Resolve futures to their values; lower PartitionedVectors."""
    import numpy as np
    from ..containers import PartitionedVector
    if is_future(obj):
        return _encode(obj.get())
    if isinstance(obj, PartitionedVector):
        return _PVMarker(np.asarray(obj.to_numpy()),
                         obj.num_partitions, obj.layout.axis)
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        vals = [_encode(x) for x in obj]
        return t(vals) if t in (list, tuple) else vals
    if isinstance(obj, dict):
        return {k: _encode(v) for k, v in obj.items()}
    return obj


def _decode(obj: Any) -> Any:
    if isinstance(obj, _PVMarker):
        return obj.restore()
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        vals = [_decode(x) for x in obj]
        return t(vals) if t in (list, tuple) else vals
    if isinstance(obj, dict):
        return {k: _decode(v) for k, v in obj.items()}
    return obj


class Checkpoint:
    """An opaque serialized argument pack (hpx::util::checkpoint)."""

    def __init__(self, data: bytes = b"") -> None:
        self.data = data

    def __len__(self) -> int:
        return len(self.data)

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, Checkpoint) and self.data == other.data

    # -- streaming (operator<< / operator>> analogs) ------------------------
    def write(self, stream: BinaryIO) -> None:
        stream.write(_MAGIC)
        stream.write(len(self.data).to_bytes(8, "little"))
        stream.write(self.data)

    @classmethod
    def read(cls, stream: BinaryIO) -> "Checkpoint":
        magic = stream.read(len(_MAGIC))
        if magic != _MAGIC:
            raise ValueError("not a hpx_tpu checkpoint stream")
        raw = stream.read(8)
        if len(raw) != 8:
            raise ValueError("truncated checkpoint stream (length header)")
        n = int.from_bytes(raw, "little")
        data = stream.read(n)
        if len(data) != n:
            raise ValueError("truncated checkpoint stream")
        return cls(data)


def save_checkpoint(*args: Any) -> Future:
    """Serialize the argument pack (futures are awaited, their VALUES are
    stored). Returns future<Checkpoint> — serialization runs as a task."""

    def build() -> Checkpoint:
        return Checkpoint(serialize(_encode(list(args))))

    return async_(build)


def save_checkpoint_sync(*args: Any) -> Checkpoint:
    return save_checkpoint(*args).get()


def restore_checkpoint(cp: Checkpoint) -> Tuple:
    """Returns the restored argument pack as a tuple (Python can't fill
    out-params; a 1-arg checkpoint restores as a 1-tuple)."""
    return tuple(_decode(deserialize(cp.data)))


def save_checkpoint_to_file(path: Union[str, os.PathLike],
                            *args: Any) -> Future:
    def build() -> Checkpoint:
        return Checkpoint(serialize(_encode(list(args))))

    def write(cp: Checkpoint) -> Checkpoint:
        import tempfile
        d = os.path.dirname(os.path.abspath(path)) or "."
        # unique temp per call: concurrent saves to one path must not
        # interleave into the same tmp file before the atomic publish
        fd, tmp = tempfile.mkstemp(dir=d, prefix=os.path.basename(
            str(path)) + ".tmp.")
        try:
            with os.fdopen(fd, "wb") as f:
                cp.write(f)
            os.replace(tmp, path)    # atomic publish
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return cp

    # serialize on the compute pool (CPU-bound), write on the "io"
    # helper pool (blocking syscalls off the scheduler workers — the
    # reference's io_service_pool split, SURVEY.md §2.1)
    from ..runtime.io_service import get_io_service_pool

    return async_(build).then(
        lambda fut: get_io_service_pool("io").async_execute(
            write, fut.get()))


def restore_checkpoint_from_file(path: Union[str, os.PathLike]) -> Tuple:
    with open(path, "rb") as f:
        return restore_checkpoint(Checkpoint.read(f))
