"""Chrome trace-event export for `svc/tracing` — Perfetto-loadable JSON.

Produces the JSON-object form of the trace-event format
(``{"traceEvents": [...]}``) that ``chrome://tracing`` and
https://ui.perfetto.dev load directly:

  * ``M`` metadata rows name the process and one row per worker thread;
  * every span is a matched ``B``/``E`` duration pair (span id and
    causal parent id in ``args`` — the task DAG survives the export);
  * every submit→run / future→continuation edge is an ``s``/``f`` flow
    pair (Perfetto draws the arrows);
  * performance-counter samples are ``C`` counter events on the same
    timeline (one track per counter name).

The exporter is also the trace's janitor: spans still open at snapshot
time get a synthetic ``E`` at the trace end, ``E``/``f`` events whose
``B``/``s`` half was evicted from the ring (drop-oldest) are discarded,
so the artifact always validates. :func:`validate_chrome_trace` is the
schema check the tests (and CI smoke) run on every emitted artifact.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["to_chrome_trace", "write_chrome_trace", "write_trace_doc",
           "merge_traces", "validate_chrome_trace", "load_chrome_trace",
           "slow_spans"]

_PID = 1                       # single-process trace; localities could
                               # map to pids in a multi-host merge


def _us(ts: float, t0: float) -> float:
    return round((ts - t0) * 1e6, 3)


def to_chrome_trace(events: List[tuple],
                    thread_names: Optional[Dict[int, str]] = None,
                    t0: float = 0.0,
                    dropped: int = 0,
                    t0_wall: Optional[float] = None) -> dict:
    """Convert a `Tracer.snapshot()` (record-order flat tuples) into
    the Chrome trace-event JSON document.  ``t0_wall`` (the tracer's
    wall-clock anchor for its monotonic ``t0``) lands in
    ``otherData.clock_sync`` so :func:`merge_traces` can align rings
    born at different times."""
    thread_names = thread_names or {}
    out: List[dict] = []
    orphans = 0                    # E/f halves whose opener was evicted

    # pass 1: which span/flow ids have their opening half in-buffer,
    # and the trace end timestamp for closing dangling spans
    begun: set = set()
    flow_started: set = set()
    t_end = t0
    for ev in events:
        ph, _name, _cat, ts, _tid, eid = ev[0], ev[1], ev[2], ev[3], \
            ev[4], ev[5]
        if ts > t_end:
            t_end = ts
        if ph == "B":
            begun.add(eid)
        elif ph == "s":
            flow_started.add(eid)

    open_spans: Dict[int, dict] = {}     # span id -> its B record
    for ev in events:
        ph, name, cat, ts, tid, eid, parent, args = ev
        if ph == "B":
            rec = {"ph": "B", "pid": _PID, "tid": tid, "ts": _us(ts, t0),
                   "name": name, "cat": cat,
                   "args": {"span": eid, "parent": parent}}
            if args:
                rec["args"].update(args)
            out.append(rec)
            open_spans[eid] = rec
        elif ph == "E":
            if eid not in begun:
                orphans += 1       # its B was evicted: keep pairs matched
                continue
            open_spans.pop(eid, None)
            out.append({"ph": "E", "pid": _PID, "tid": tid,
                        "ts": _us(ts, t0), "name": name, "cat": cat})
        elif ph == "i":
            rec = {"ph": "i", "pid": _PID, "tid": tid, "ts": _us(ts, t0),
                   "name": name, "cat": cat, "s": "t",
                   "args": {"parent": parent}}
            if args:
                rec["args"].update(args)
            out.append(rec)
        elif ph == "s":
            out.append({"ph": "s", "pid": _PID, "tid": tid,
                        "ts": _us(ts, t0), "name": name, "cat": cat,
                        "id": eid})
        elif ph == "f":
            if eid not in flow_started:
                orphans += 1       # unresolved arrow: drop the head
                continue
            out.append({"ph": "f", "pid": _PID, "tid": tid,
                        "ts": _us(ts, t0), "name": name, "cat": cat,
                        "id": eid, "bp": "e"})
        elif ph == "C":
            out.append({"ph": "C", "pid": _PID, "tid": 0,
                        "ts": _us(ts, t0), "name": name, "cat": cat,
                        "args": {"value": args}})

    # drop flow tails whose head span never ran (task still queued at
    # snapshot): validators demand every s resolve to an f
    finished = {e["id"] for e in out if e["ph"] == "f"}
    kept = [e for e in out if e["ph"] != "s" or e["id"] in finished]
    orphans += len(out) - len(kept)
    out = kept

    # close spans still open at snapshot so B/E always balance —
    # innermost (most recent B) first, preserving stack nesting
    for sid, rec in reversed(list(open_spans.items())):
        out.append({"ph": "E", "pid": _PID, "tid": rec["tid"],
                    "ts": _us(t_end, t0), "name": rec["name"],
                    "cat": rec["cat"]})

    # stable sort by ts: per-thread record order (already
    # non-decreasing) is preserved, threads interleave correctly
    out.sort(key=lambda e: e["ts"])

    meta: List[dict] = [{
        "ph": "M", "pid": _PID, "tid": 0, "name": "process_name",
        "args": {"name": "hpx_tpu"}}]
    for ident, tname in sorted(thread_names.items()):
        meta.append({"ph": "M", "pid": _PID, "tid": ident,
                     "name": "thread_name", "args": {"name": tname}})

    # janitor summary: ring drops (satellite of the
    # /runtime{...}/trace/dropped-spans counter), orphans discarded,
    # dangling spans synthetically closed — an artifact that "validates"
    # after heavy repair should say so
    other: Dict[str, Any] = {
        "dropped_events": dropped,
        "format": "hpx_tpu.svc.tracing",
        "janitor": {"orphan_events_discarded": orphans,
                    "spans_closed_at_end": len(open_spans)},
    }
    if t0_wall is not None:
        other["clock_sync"] = {"t0_wall": t0_wall}
    return {"traceEvents": meta + out,
            "displayTimeUnit": "ms",
            "otherData": other}


def slow_spans(events: List[tuple], t0: float = 0.0,
               limit: int = 32) -> List[dict]:
    """Top-``limit`` longest COMPLETED spans in a ``Tracer.snapshot()``
    — the /tracez sample: pair B/E halves by span id and sort by
    duration (ties broken by start then id, so the answer is
    deterministic for a fixed ring).  Spans whose opener was evicted
    from the ring are skipped, like :func:`to_chrome_trace` orphans."""
    opens: Dict[int, tuple] = {}
    done: List[dict] = []
    for ev in events:
        ph, _name, _cat, ts, tid, eid = ev[0], ev[1], ev[2], ev[3], \
            ev[4], ev[5]
        if ph == "B":
            opens[eid] = ev
        elif ph == "E":
            b = opens.pop(eid, None)
            if b is not None:
                done.append({
                    "name": b[1], "cat": b[2],
                    "dur_s": round(ts - b[3], 9),
                    "start_s": round(b[3] - t0, 9),
                    "tid": tid, "id": eid,
                    "args": b[7] or {},
                })
    done.sort(key=lambda d: (-d["dur_s"], d["start_s"], d["id"]))
    return done[: max(0, int(limit))]


def write_trace_doc(path: str, doc: dict) -> dict:
    """Atomically write an already-built trace document."""
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    os.replace(tmp, path)          # readers never see a half-written trace
    return doc


def write_chrome_trace(path: str, tracer: Any) -> dict:
    """Snapshot `tracer` and write the JSON artifact to `path`."""
    doc = to_chrome_trace(tracer.snapshot(), tracer.thread_names(),
                          tracer.t0, tracer.dropped,
                          t0_wall=getattr(tracer, "t0_wall", None))
    return write_trace_doc(path, doc)


def merge_traces(docs: List[Tuple[str, dict]]) -> dict:
    """Stitch several exported trace documents — the router's process
    tracer plus every worker's private ring — into ONE Perfetto
    document.

    * Each input becomes its own pid row (pid = position + 1) named by
      its label via a ``process_name`` metadata row; per-doc thread
      rows ride along under the new pid.
    * Clocks align through each doc's ``otherData.clock_sync.t0_wall``
      wall anchor: timestamps shift by the anchor delta against the
      earliest anchor (a doc without an anchor keeps its own zero).
    * Flow ids are namespaced per doc (``"<i>:<id>"``) so rings that
      each counted from 1 do not weld unrelated arrows together.
    * Request stitching: B spans carrying a string ``rid`` arg are
      grouped per rid across ALL docs and consecutive spans landing in
      DIFFERENT pids get a fresh ``s``/``f`` flow pair — the
      place → prefill → transfer → decode arrows that cross worker
      rows.  (ContinuousServer's slot-local integer rids never collide
      with the router's "r<N>" strings, so in-worker spans do not
      false-link across workers.)

    The result passes :func:`validate_chrome_trace`.
    """
    meta: List[dict] = []
    merged: List[dict] = []
    anchors = [d.get("otherData", {}).get("clock_sync", {})
               .get("t0_wall") for _, d in docs]
    known = [a for a in anchors if a is not None]
    ref = min(known) if known else 0.0
    dropped = 0
    per_process: Dict[str, int] = {}
    # rid -> [(ts, pid, tid, span name)] over every doc's B events
    rid_spans: Dict[str, List[Tuple[float, int, int, str]]] = {}

    for i, (label, doc) in enumerate(docs):
        pid = i + 1
        off = (anchors[i] - ref) * 1e6 if anchors[i] is not None else 0.0
        meta.append({"ph": "M", "pid": pid, "tid": 0,
                     "name": "process_name", "args": {"name": label}})
        od = doc.get("otherData", {})
        dropped += int(od.get("dropped_events", 0) or 0)
        per_process[label] = int(od.get("dropped_events", 0) or 0)
        for ev in doc.get("traceEvents", []):
            ph = ev.get("ph")
            if ph == "M":
                if ev.get("name") == "process_name":
                    continue       # replaced by the labelled row above
                e2 = dict(ev)
                e2["pid"] = pid
                meta.append(e2)
                continue
            e2 = dict(ev)
            e2["pid"] = pid
            e2["ts"] = round(ev["ts"] + off, 3)
            if ph in ("s", "f"):
                e2["id"] = f"{i}:{ev['id']}"
            merged.append(e2)
            if ph == "B":
                rid = (ev.get("args") or {}).get("rid")
                if isinstance(rid, str):
                    rid_spans.setdefault(rid, []).append(
                        (e2["ts"], pid, ev.get("tid", 0),
                         ev.get("name", "")))

    arrows: List[dict] = []
    fid_seq = 0
    stitched_rids = 0
    for rid in sorted(rid_spans):
        spans = sorted(rid_spans[rid])
        crossed = False
        for (ts0, p0, tid0, _n0), (ts1, p1, tid1, _n1) in \
                zip(spans, spans[1:]):
            if p0 == p1:
                continue
            fid = f"rid:{rid}:{fid_seq}"
            fid_seq += 1
            crossed = True
            arrows.append({"ph": "s", "pid": p0, "tid": tid0, "ts": ts0,
                           "name": "rid-flow", "cat": "rid", "id": fid})
            arrows.append({"ph": "f", "pid": p1, "tid": tid1, "ts": ts1,
                           "name": "rid-flow", "cat": "rid", "id": fid,
                           "bp": "e"})
        if crossed:
            stitched_rids += 1
    merged.extend(arrows)
    merged.sort(key=lambda e: e["ts"])

    return {"traceEvents": meta + merged,
            "displayTimeUnit": "ms",
            "otherData": {"format": "hpx_tpu.svc.tracing/merged",
                          "processes": [label for label, _ in docs],
                          "dropped_events": dropped,
                          "dropped_events_per_process": per_process,
                          "stitched_rids": stitched_rids,
                          "rid_flow_arrows": len(arrows) // 2}}


def load_chrome_trace(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def validate_chrome_trace(doc: Any) -> List[str]:
    """Schema-check an exported document; returns a list of problems
    (empty == valid). Checks: required keys per phase, globally
    non-decreasing timestamps, matched B/E pairs per thread, every
    flow id resolving to an s+f pair, numeric counter values."""
    problems: List[str] = []
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        return ["document is not a dict with a traceEvents list"]
    events = doc["traceEvents"]
    if not isinstance(events, list):
        return ["traceEvents is not a list"]

    required = {"B": ("name", "cat", "ts", "pid", "tid"),
                "E": ("name", "ts", "pid", "tid"),
                "i": ("name", "ts", "pid", "tid"),
                "s": ("name", "ts", "pid", "tid", "id"),
                "f": ("name", "ts", "pid", "tid", "id"),
                "C": ("name", "ts", "pid", "args"),
                "M": ("name", "pid", "args")}
    last_ts: Optional[float] = None
    depth: Dict[Tuple[int, int], int] = {}     # (pid, tid) -> open B count
    flows: Dict[int, set] = {}
    for i, ev in enumerate(events):
        ph = ev.get("ph")
        if ph not in required:
            problems.append(f"event {i}: unknown/missing ph {ph!r}")
            continue
        missing = [k for k in required[ph] if k not in ev]
        if missing:
            problems.append(f"event {i} (ph={ph}): missing {missing}")
            continue
        if ph == "M":
            continue
        ts = ev["ts"]
        if not isinstance(ts, (int, float)):
            problems.append(f"event {i}: non-numeric ts {ts!r}")
            continue
        if last_ts is not None and ts < last_ts:
            problems.append(
                f"event {i}: ts {ts} < previous {last_ts} — "
                "not monotonically ordered")
        last_ts = ts
        key = (ev["pid"], ev["tid"])
        if ph == "B":
            depth[key] = depth.get(key, 0) + 1
        elif ph == "E":
            depth[key] = depth.get(key, 0) - 1
            if depth[key] < 0:
                problems.append(
                    f"event {i}: E without a matching B on tid "
                    f"{ev['tid']}")
        elif ph in ("s", "f"):
            flows.setdefault(ev["id"], set()).add(ph)
        elif ph == "C":
            v = ev["args"].get("value")
            if not isinstance(v, (int, float)):
                problems.append(
                    f"event {i}: counter {ev['name']!r} value {v!r} "
                    "is not numeric")
    for key, d in depth.items():
        if d != 0:
            problems.append(f"tid {key[1]}: {d} unmatched B events")
    for fid, phases in flows.items():
        if phases != {"s", "f"}:
            problems.append(
                f"flow id {fid}: has {sorted(phases)}, needs both "
                "s and f")
    return problems
