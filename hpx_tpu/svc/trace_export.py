"""Chrome trace-event export for `svc/tracing` — Perfetto-loadable JSON.

Produces the JSON-object form of the trace-event format
(``{"traceEvents": [...]}``) that ``chrome://tracing`` and
https://ui.perfetto.dev load directly:

  * ``M`` metadata rows name the process and one row per worker thread;
  * every span is a matched ``B``/``E`` duration pair (span id and
    causal parent id in ``args`` — the task DAG survives the export);
  * every submit→run / future→continuation edge is an ``s``/``f`` flow
    pair (Perfetto draws the arrows);
  * performance-counter samples are ``C`` counter events on the same
    timeline (one track per counter name).

The exporter is also the trace's janitor: spans still open at snapshot
time get a synthetic ``E`` at the trace end, ``E``/``f`` events whose
``B``/``s`` half was evicted from the ring (drop-oldest) are discarded,
so the artifact always validates. :func:`validate_chrome_trace` is the
schema check the tests (and CI smoke) run on every emitted artifact.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["to_chrome_trace", "write_chrome_trace",
           "validate_chrome_trace", "load_chrome_trace"]

_PID = 1                       # single-process trace; localities could
                               # map to pids in a multi-host merge


def _us(ts: float, t0: float) -> float:
    return round((ts - t0) * 1e6, 3)


def to_chrome_trace(events: List[tuple],
                    thread_names: Optional[Dict[int, str]] = None,
                    t0: float = 0.0,
                    dropped: int = 0) -> dict:
    """Convert a `Tracer.snapshot()` (record-order flat tuples) into
    the Chrome trace-event JSON document."""
    thread_names = thread_names or {}
    out: List[dict] = []

    # pass 1: which span/flow ids have their opening half in-buffer,
    # and the trace end timestamp for closing dangling spans
    begun: set = set()
    flow_started: set = set()
    t_end = t0
    for ev in events:
        ph, _name, _cat, ts, _tid, eid = ev[0], ev[1], ev[2], ev[3], \
            ev[4], ev[5]
        if ts > t_end:
            t_end = ts
        if ph == "B":
            begun.add(eid)
        elif ph == "s":
            flow_started.add(eid)

    open_spans: Dict[int, dict] = {}     # span id -> its B record
    for ev in events:
        ph, name, cat, ts, tid, eid, parent, args = ev
        if ph == "B":
            rec = {"ph": "B", "pid": _PID, "tid": tid, "ts": _us(ts, t0),
                   "name": name, "cat": cat,
                   "args": {"span": eid, "parent": parent}}
            if args:
                rec["args"].update(args)
            out.append(rec)
            open_spans[eid] = rec
        elif ph == "E":
            if eid not in begun:
                continue           # its B was evicted: keep pairs matched
            open_spans.pop(eid, None)
            out.append({"ph": "E", "pid": _PID, "tid": tid,
                        "ts": _us(ts, t0), "name": name, "cat": cat})
        elif ph == "i":
            rec = {"ph": "i", "pid": _PID, "tid": tid, "ts": _us(ts, t0),
                   "name": name, "cat": cat, "s": "t",
                   "args": {"parent": parent}}
            if args:
                rec["args"].update(args)
            out.append(rec)
        elif ph == "s":
            out.append({"ph": "s", "pid": _PID, "tid": tid,
                        "ts": _us(ts, t0), "name": name, "cat": cat,
                        "id": eid})
        elif ph == "f":
            if eid not in flow_started:
                continue           # unresolved arrow: drop the head
            out.append({"ph": "f", "pid": _PID, "tid": tid,
                        "ts": _us(ts, t0), "name": name, "cat": cat,
                        "id": eid, "bp": "e"})
        elif ph == "C":
            out.append({"ph": "C", "pid": _PID, "tid": 0,
                        "ts": _us(ts, t0), "name": name, "cat": cat,
                        "args": {"value": args}})

    # drop flow tails whose head span never ran (task still queued at
    # snapshot): validators demand every s resolve to an f
    finished = {e["id"] for e in out if e["ph"] == "f"}
    out = [e for e in out if e["ph"] != "s" or e["id"] in finished]

    # close spans still open at snapshot so B/E always balance —
    # innermost (most recent B) first, preserving stack nesting
    for sid, rec in reversed(list(open_spans.items())):
        out.append({"ph": "E", "pid": _PID, "tid": rec["tid"],
                    "ts": _us(t_end, t0), "name": rec["name"],
                    "cat": rec["cat"]})

    # stable sort by ts: per-thread record order (already
    # non-decreasing) is preserved, threads interleave correctly
    out.sort(key=lambda e: e["ts"])

    meta: List[dict] = [{
        "ph": "M", "pid": _PID, "tid": 0, "name": "process_name",
        "args": {"name": "hpx_tpu"}}]
    for ident, tname in sorted(thread_names.items()):
        meta.append({"ph": "M", "pid": _PID, "tid": ident,
                     "name": "thread_name", "args": {"name": tname}})

    return {"traceEvents": meta + out,
            "displayTimeUnit": "ms",
            "otherData": {"dropped_events": dropped,
                          "format": "hpx_tpu.svc.tracing"}}


def write_chrome_trace(path: str, tracer: Any) -> dict:
    """Snapshot `tracer` and write the JSON artifact to `path`."""
    doc = to_chrome_trace(tracer.snapshot(), tracer.thread_names(),
                          tracer.t0, tracer.dropped)
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    os.replace(tmp, path)          # readers never see a half-written trace
    return doc


def load_chrome_trace(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def validate_chrome_trace(doc: Any) -> List[str]:
    """Schema-check an exported document; returns a list of problems
    (empty == valid). Checks: required keys per phase, globally
    non-decreasing timestamps, matched B/E pairs per thread, every
    flow id resolving to an s+f pair, numeric counter values."""
    problems: List[str] = []
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        return ["document is not a dict with a traceEvents list"]
    events = doc["traceEvents"]
    if not isinstance(events, list):
        return ["traceEvents is not a list"]

    required = {"B": ("name", "cat", "ts", "pid", "tid"),
                "E": ("name", "ts", "pid", "tid"),
                "i": ("name", "ts", "pid", "tid"),
                "s": ("name", "ts", "pid", "tid", "id"),
                "f": ("name", "ts", "pid", "tid", "id"),
                "C": ("name", "ts", "pid", "args"),
                "M": ("name", "pid", "args")}
    last_ts: Optional[float] = None
    depth: Dict[Tuple[int, int], int] = {}     # (pid, tid) -> open B count
    flows: Dict[int, set] = {}
    for i, ev in enumerate(events):
        ph = ev.get("ph")
        if ph not in required:
            problems.append(f"event {i}: unknown/missing ph {ph!r}")
            continue
        missing = [k for k in required[ph] if k not in ev]
        if missing:
            problems.append(f"event {i} (ph={ph}): missing {missing}")
            continue
        if ph == "M":
            continue
        ts = ev["ts"]
        if not isinstance(ts, (int, float)):
            problems.append(f"event {i}: non-numeric ts {ts!r}")
            continue
        if last_ts is not None and ts < last_ts:
            problems.append(
                f"event {i}: ts {ts} < previous {last_ts} — "
                "not monotonically ordered")
        last_ts = ts
        key = (ev["pid"], ev["tid"])
        if ph == "B":
            depth[key] = depth.get(key, 0) + 1
        elif ph == "E":
            depth[key] = depth.get(key, 0) - 1
            if depth[key] < 0:
                problems.append(
                    f"event {i}: E without a matching B on tid "
                    f"{ev['tid']}")
        elif ph in ("s", "f"):
            flows.setdefault(ev["id"], set()).add(ph)
        elif ph == "C":
            v = ev["args"].get("value")
            if not isinstance(v, (int, float)):
                problems.append(
                    f"event {i}: counter {ev['name']!r} value {v!r} "
                    "is not numeric")
    for key, d in depth.items():
        if d != 0:
            problems.append(f"tid {key[1]}: {d} unmatched B events")
    for fid, phases in flows.items():
        if phases != {"s", "f"}:
            problems.append(
                f"flow id {fid}: has {sorted(phases)}, needs both "
                "s and f")
    return problems
