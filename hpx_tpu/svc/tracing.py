"""Causal task tracer — ring-buffered spans with parentage across futures.

Reference analog: APEX's task-dependency capture over the HPX external
timer hooks (libs/core/threading_base fires task create/start/stop into
`util::external_timer`; APEX reconstructs the task DAG and emits OTF2 /
Google-trace timelines). Here the same hook plumbing
(`svc/profiling.register_external_timer`) feeds a :class:`Tracer` that
records, into a bounded drop-oldest ring:

  * B/E duration spans for every pool task (named via profiling's
    ``_unwrap`` attribution), every ``.then()`` continuation, and every
    explicitly annotated region (:func:`span`);
  * the CAUSAL parent of each span — the span that was live on the
    submitting thread when the work was scheduled — threaded through
    ``runtime/threadpool.py`` (a fourth task-tuple slot) and
    ``futures/future.py`` (continuation wrapping), so ``post``/
    ``async_`` fan-outs, ``.then()`` chains and ``when_all`` joins form
    a reconstructable DAG;
  * flow events (the Chrome ``s``/``f`` arrow pair) for every
    submit→run and future→continuation edge;
  * periodic performance-counter samples (``/serving``, ``/cache``,
    ``/threads`` queue depth, …) interleaved on the same timeline.

`svc/trace_export.py` turns the ring into Chrome trace-event JSON that
loads directly in ``chrome://tracing`` / Perfetto.

Zero-overhead discipline: everything is OFF by default. The
instrumented hot paths (pool submit, ``Future.then``, serving steps,
radix match) each pay one module-global load plus an ``is None`` test
when no tracer is active — no allocation, no lock, no call. The ring
itself is append-only under the GIL (no lock on the event path); the
drop counter is best-effort under concurrent appends.

Config (``core/config.py`` DEFAULTS, all under ``hpx.trace.*``)::

    hpx.trace.enabled          0        start_if_configured() gate
    hpx.trace.buffer_events    65536    ring capacity (drop-oldest)
    hpx.trace.counter_interval 0.05     seconds between counter samples
    hpx.trace.counters         /serving*,/cache*,/threads*,/programs*
"""

from __future__ import annotations

import contextlib
import itertools
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "Tracer", "TaskCtx", "active_tracer", "start_tracing",
    "stop_tracing", "start_if_configured", "trace", "span", "instant",
    "current_span_id", "flow_begin", "flow_end",
]

# Ring entries are flat 8-tuples — the cheapest thing CPython can
# append — decoded only at export time:
#   (ph, name, cat, ts, tid, id, parent, args)
# ph: "B"/"E" span begin/end (id = span id), "i" instant,
#     "s"/"f" flow start/finish (id = flow id), "C" counter sample
#     (args = value).
_Event = Tuple[str, str, str, float, int, Optional[int], Optional[int],
               Any]


class TaskCtx:
    """Causal context captured on the submitting thread: the parent
    span id plus a pre-allocated flow-arrow id (None when the submit
    happened outside any span — there is no slice to anchor the
    arrow)."""

    __slots__ = ("parent", "flow", "name")

    def __init__(self, parent: Optional[int], flow: Optional[int],
                 name: str) -> None:
        self.parent = parent
        self.flow = flow
        self.name = name


class _NullSpan:
    """The shared no-op returned by module-level span() when tracing is
    off — one immortal object, so the disabled path allocates nothing."""

    __slots__ = ()
    id = None

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc: Any) -> bool:
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """Context manager recording one B/E pair; nesting via the
    tracer's per-thread span stack gives the parent id."""

    __slots__ = ("_tr", "name", "cat", "args", "id")

    def __init__(self, tr: "Tracer", name: str, cat: str,
                 args: Optional[dict]) -> None:
        self._tr = tr
        self.name = name
        self.cat = cat
        self.args = args
        self.id: Optional[int] = None

    def __enter__(self) -> "_Span":
        self.id = self._tr._begin(self.name, self.cat, self.args)
        return self

    def __exit__(self, *exc: Any) -> bool:
        self._tr._end(self.name, self.cat, self.id)
        return False


def _qualname(fn: Any) -> str:
    return getattr(fn, "__qualname__", None) or repr(fn)


class Tracer:
    """Lock-cheap ring-buffered event tracer.

    One instance is active process-wide (module slot ``_active``);
    :meth:`start` installs it into the external-timer registry (pool
    task spans), the threadpool submit capture (causal parents + flow
    arrows) and the future continuation hook, and starts the counter
    sampler; :meth:`stop` removes every hook. Recording methods are
    safe to call from any thread.
    """

    def __init__(self, capacity: int = 65536,
                 counter_interval: float = 0.05,
                 counter_patterns: Optional[List[str]] = None,
                 sample_counters: bool = True) -> None:
        if capacity < 2:
            raise ValueError(f"capacity must be >= 2, got {capacity}")
        self.capacity = int(capacity)
        self._buf: deque = deque(maxlen=self.capacity)
        self.dropped = 0           # best-effort under concurrent appends
        self._ids = itertools.count(1)     # span AND flow ids (shared)
        self._tls = threading.local()
        self._threads: Dict[int, str] = {}   # ident -> thread name
        self.t0 = time.perf_counter()
        # wall anchor taken at the same instant as t0: trace_export
        # merge_traces aligns rings born at different times by shifting
        # each doc's monotonic timestamps with the wall-anchor delta
        self.t0_wall = time.time()
        self.counter_interval = float(counter_interval)
        self.counter_patterns = list(counter_patterns or [])
        self._sample_counters = bool(sample_counters)
        self._sampler_stop: Optional[threading.Event] = None
        self._sampler: Optional[threading.Thread] = None
        self._started = False

    # -- event path (hot; no locks) -------------------------------------

    def _record(self, ev: _Event) -> None:
        buf = self._buf
        if len(buf) == self.capacity:
            self.dropped += 1      # deque(maxlen) drops the oldest
        buf.append(ev)

    def _tid(self) -> int:
        ident = threading.get_ident()
        if ident not in self._threads:
            self._threads[ident] = threading.current_thread().name
        return ident

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _begin(self, name: str, cat: str, args: Optional[dict],
               parent: Optional[int] = None,
               flow: Optional[int] = None,
               flow_name: str = "") -> int:
        st = self._stack()
        if parent is None and st:
            parent = st[-1]
        sid = next(self._ids)
        tid = self._tid()
        ts = time.perf_counter()
        self._record(("B", name, cat, ts, tid, sid, parent, args))
        if flow is not None:
            # the arrow head binds to the slice just opened (same ts)
            self._record(("f", flow_name or name, "flow", ts, tid,
                          flow, None, None))
        st.append(sid)
        return sid

    def _end(self, name: str, cat: str, sid: Optional[int]) -> None:
        if sid is None:
            return
        st = self._stack()
        if st:
            if st[-1] == sid:
                st.pop()
            elif sid in st:        # misnested exit: drop it anyway
                st.remove(sid)
        self._record(("E", name, cat, time.perf_counter(), self._tid(),
                      sid, None, None))

    # -- public recording API -------------------------------------------

    def span(self, name: str, cat: str = "user", **args: Any) -> _Span:
        """``with tracer.span("phase"): ...`` — records a B/E pair;
        nested spans parent automatically."""
        return _Span(self, name, cat, args or None)

    def instant(self, name: str, cat: str = "user", **args: Any) -> None:
        """Point event, parented to the enclosing span (if any)."""
        st = self._stack()
        parent = st[-1] if st else None
        self._record(("i", name, cat, time.perf_counter(), self._tid(),
                      None, parent, args or None))

    def counter(self, name: str, value: float) -> None:
        """One counter sample on the shared timeline."""
        self._record(("C", name, "counter", time.perf_counter(), 0,
                      None, None, float(value)))

    def current_span_id(self) -> Optional[int]:
        st = self._stack()
        return st[-1] if st else None

    def flow_begin(self, name: str, cat: str = "flow") -> Optional[int]:
        """Emit the source half of a flow arrow anchored at the
        current slice; returns the flow id for :meth:`flow_end`.
        Returns None outside any span (no slice to anchor to) — the
        export janitor would drop a danging arrow anyway."""
        st = self._stack()
        if not st:
            return None
        fid = next(self._ids)
        self._record(("s", name, cat, time.perf_counter(), self._tid(),
                      fid, None, None))
        return fid

    def flow_end(self, fid: Optional[int], name: str,
                 cat: str = "flow") -> None:
        """Bind the arrow head of flow `fid` to the current slice.
        No-op for fid None (flow_begin outside a span) — callers can
        thread the id through unconditionally."""
        if fid is None or not self._stack():
            return
        self._record(("f", name, cat, time.perf_counter(), self._tid(),
                      fid, None, None))

    # -- causal capture (submit side) -----------------------------------

    def capture(self, fn: Any = None, args: tuple = ()) -> Optional[TaskCtx]:
        """Called on the SUBMITTING thread (threadpool submit hook /
        ``Future.then``): snapshot the current span as the causal
        parent and emit the flow-arrow tail inside it. Returns None
        when no span is live — nothing to parent to."""
        st = self._stack()
        if not st:
            return None
        parent = st[-1]
        from .profiling import _unwrap
        name = _qualname(_unwrap(fn, args)) if fn is not None else "task"
        fid = next(self._ids)
        self._record(("s", name, "flow", time.perf_counter(),
                      self._tid(), fid, None, None))
        return TaskCtx(parent, fid, name)

    # -- external-timer hook (pool task spans) --------------------------
    # profiling._emit calls these with the _unwrap'ed user function.

    def on_start(self, fn: Any) -> None:
        ctx = getattr(self._tls, "pending", None)
        if ctx is not None:
            self._tls.pending = None
        self._begin(_qualname(fn), "task", None,
                    parent=ctx.parent if ctx else None,
                    flow=ctx.flow if ctx else None,
                    flow_name=ctx.name if ctx else "")

    def on_stop(self, fn: Any, seconds: float) -> None:
        st = self._stack()
        if not st:
            return                 # started before the tracer attached
        self._end(_qualname(fn), "task", st[-1])

    def _set_pending(self, ctx: Optional[TaskCtx]) -> None:
        """Worker side of the handoff: the threadpool parks the task's
        captured ctx here just before the start event fires."""
        self._tls.pending = ctx

    # -- continuation wrapping (futures side) ---------------------------

    def wrap_continuation(self, run: Any, user_fn: Any) -> Any:
        """Wrap a ``Future.then`` continuation so its execution records
        a span parented to the ATTACHING context with a flow arrow from
        the attach site to the run site."""
        ctx = self.capture(user_fn)
        name = f"then:{_qualname(user_fn)}"

        def traced(st: Any) -> None:
            tr = _active
            if tr is not self:     # tracer stopped in the meantime
                run(st)
                return
            sid = self._begin(name, "continuation", None,
                              parent=ctx.parent if ctx else None,
                              flow=ctx.flow if ctx else None,
                              flow_name=ctx.name if ctx else "")
            try:
                run(st)
            finally:
                self._end(name, "continuation", sid)
        return traced

    # -- counter sampler -------------------------------------------------

    def _sample_once(self) -> None:
        from .performance_counters import query_counters
        for pattern in self.counter_patterns:
            try:
                for name, cv in query_counters(pattern).items():
                    self.counter(name, cv.value)
            except Exception:  # noqa: BLE001 — sampling must never die
                pass

    def _sampler_loop(self, stop: threading.Event) -> None:
        while not stop.wait(self.counter_interval):
            self._sample_once()

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "Tracer":
        """Install every hook; idempotent."""
        if self._started:
            return self
        self._started = True
        from . import profiling
        from ..futures import future as _future
        from ..runtime import threadpool as _tp
        # spans for pool tasks ride the EXISTING external-timer
        # plumbing (this also flips pool instrumentation on)
        profiling.register_external_timer(self)
        # causal parents + flow arrows need the submit-side capture
        _tp.set_trace_hooks(self.capture, self._set_pending)
        _future.set_trace_continuation_hook(self.wrap_continuation)
        if self._sample_counters and self.counter_patterns \
                and self.counter_interval > 0:
            self._sampler_stop = threading.Event()
            self._sampler = threading.Thread(
                target=self._sampler_loop, args=(self._sampler_stop,),
                name="hpx-trace-sampler", daemon=True)
            self._sampler.start()
        return self

    def stop(self) -> "Tracer":
        """Remove every hook and stop the sampler; the buffer stays
        readable (snapshot/export after stop is the normal flow)."""
        if not self._started:
            return self
        self._started = False
        from . import profiling
        from ..futures import future as _future
        from ..runtime import threadpool as _tp
        profiling.unregister_external_timer(self)
        _tp.set_trace_hooks(None, None)
        _future.set_trace_continuation_hook(None)
        if self._sampler_stop is not None:
            self._sampler_stop.set()
            self._sampler.join(timeout=2.0)
            self._sampler_stop = None
            self._sampler = None
            self._sample_once()    # one final sample closes the tracks
        return self

    # -- inspection / export ---------------------------------------------

    def snapshot(self) -> List[_Event]:
        """Copy of the ring in record order. Safe after stop(); under
        live concurrent appends the copy retries (deque iteration
        raises if mutated mid-copy)."""
        for _ in range(8):
            try:
                return list(self._buf)
            except RuntimeError:   # mutated during iteration
                continue
        return list(self._buf)     # last try propagates if still racing

    def thread_names(self) -> Dict[int, str]:
        return dict(self._threads)

    def export(self, path: str) -> dict:
        """Write Chrome trace-event JSON; returns the document."""
        from .trace_export import write_chrome_trace
        return write_chrome_trace(path, self)


# ---------------------------------------------------------------------------
# module-level active tracer + convenience API
# ---------------------------------------------------------------------------

_active: Optional[Tracer] = None


def active_tracer() -> Optional[Tracer]:
    """The live tracer, or None — the ONE check every instrumentation
    point makes before doing any work."""
    return _active


def current_span_id() -> Optional[int]:
    tr = _active
    return tr.current_span_id() if tr is not None else None


def start_tracing(capacity: Optional[int] = None,
                  counter_interval: Optional[float] = None,
                  counter_patterns: Optional[List[str]] = None,
                  sample_counters: bool = True) -> Tracer:
    """Create, install and return the process tracer. Defaults come
    from the ``hpx.trace.*`` config keys. Raises if one is active."""
    global _active
    if _active is not None:
        raise RuntimeError("tracing already active; stop_tracing() first")
    from ..core.config import runtime_config
    rc = runtime_config()
    if capacity is None:
        capacity = rc.get_int("hpx.trace.buffer_events", 65536)
    if counter_interval is None:
        counter_interval = rc.get_float("hpx.trace.counter_interval",
                                        0.05)
    if counter_patterns is None:
        raw = rc.get("hpx.trace.counters",
                     "/serving*,/cache*,/threads*,/programs*") or ""
        counter_patterns = [p.strip() for p in raw.split(",")
                            if p.strip()]
    tr = Tracer(capacity=capacity, counter_interval=counter_interval,
                counter_patterns=counter_patterns,
                sample_counters=sample_counters)
    _active = tr
    tr.start()
    return tr


def stop_tracing() -> Optional[Tracer]:
    """Stop and detach the active tracer (returned for export)."""
    global _active
    tr = _active
    _active = None
    if tr is not None:
        tr.stop()
    return tr


def start_if_configured() -> Optional[Tracer]:
    """Start tracing iff ``hpx.trace.enabled`` is truthy and no tracer
    is active — the config-gated entry point bench harnesses use."""
    from ..core.config import runtime_config
    if _active is not None:
        return _active
    if not runtime_config().get_bool("hpx.trace.enabled", False):
        return None
    return start_tracing()


@contextlib.contextmanager
def trace(capacity: Optional[int] = None,
          counter_interval: Optional[float] = None,
          counter_patterns: Optional[List[str]] = None,
          sample_counters: bool = True):
    """Scoped tracing: ``with trace() as tr: ...; tr.export(path)``."""
    tr = start_tracing(capacity, counter_interval, counter_patterns,
                       sample_counters)
    try:
        yield tr
    finally:
        stop_tracing()


def span(name: str, cat: str = "user", **args: Any):
    """Module-level span: a real span under an active tracer, the
    shared no-op object otherwise (the instrumentation call sites'
    single entry point)."""
    tr = _active
    if tr is None:
        return _NULL_SPAN
    return tr.span(name, cat, **args)


def null_span() -> _NullSpan:
    """The shared no-op span, for instrumentation that keeps its OWN
    ring (disagg worker rings) and needs the do-nothing branch when
    process tracing is off."""
    return _NULL_SPAN


def instant(name: str, cat: str = "user", **args: Any) -> None:
    tr = _active
    if tr is not None:
        tr.instant(name, cat, **args)


def flow_begin(name: str, cat: str = "flow") -> Optional[int]:
    """Module-level flow-arrow tail: links the current slice to a later
    one across steps/threads (serving uses it to tie an admit span to
    the chunked-prefill spans it scheduled). None when tracing is off
    or no span is live; feed the result to :func:`flow_end` as-is."""
    tr = _active
    return tr.flow_begin(name, cat) if tr is not None else None


def flow_end(fid: Optional[int], name: str, cat: str = "flow") -> None:
    """Module-level flow-arrow head; no-op when off or fid is None."""
    tr = _active
    if tr is not None:
        tr.flow_end(fid, name, cat)
