"""Task-level software resiliency (SURVEY.md §2.5/§5.3).

Reference analog: libs/core/resiliency + libs/full/resiliency_distributed:
  async_replay(n, f, ...)            re-run up to n times on exception
  async_replay_validate(n, pred, f)  ...or on validation failure
  async_replicate(n, f, ...)         run n concurrent copies, first good
  async_replicate_validate / _vote   validated / voted consensus result
  replay_executor / replicate_executor   executor wrappers
  distributed replay                 retarget other localities per attempt

TPU-first notes: a "task" here is a host callable whose payload is
usually a device dispatch; XLA programs are deterministic, so replay
guards against transient HOST/runtime failures and validation guards
against numerical corruption (the reference's use case is identical).
Replicate+vote runs the copies concurrently through the task pool and
elects by value equality (arrays compare by bytes).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional, Sequence

from ..core.errors import Error, HpxError
from ..futures.async_ import async_, post as _post
from ..futures.combinators import when_all
from ..futures.future import Future, SharedState


class AbortReplayException(HpxError):
    """Raised by a task to stop further replays (hpx::resiliency analog)."""

    def __init__(self, msg: str = "replay aborted") -> None:
        super().__init__(Error.yield_aborted, msg)


class AbortReplicateException(AbortReplayException):
    pass


class ReplayValidationError(HpxError):
    def __init__(self, attempts: int) -> None:
        super().__init__(Error.invalid_status,
                         f"validation failed on all {attempts} replays")
        self.attempts = attempts


class ReplicateVotingError(HpxError):
    def __init__(self, msg: str) -> None:
        super().__init__(Error.invalid_status, msg)


# ---------------------------------------------------------------------------
# replay
# ---------------------------------------------------------------------------

def default_replay_n() -> int:
    """Attempt count used when a replay API is called with ``n=None`` —
    the hpx.resiliency.replay_default_n knob."""
    from ..core.config import runtime_config
    return runtime_config().get_int("hpx.resiliency.replay_default_n", 3)


def _resolve_n(n: Optional[int]) -> int:
    return default_replay_n() if n is None else n


def _replay_loop(n: int, validate: Optional[Callable[[Any], bool]],
                 fn: Callable[..., Any], args: tuple, kwargs: dict) -> Any:
    last_exc: Optional[BaseException] = None
    for _attempt in range(n):
        try:
            result = fn(*args, **kwargs)
        except AbortReplayException:
            raise
        except BaseException as e:  # noqa: BLE001
            last_exc = e
            continue
        if validate is None or validate(result):
            return result
        last_exc = None
    if last_exc is not None:
        raise last_exc
    raise ReplayValidationError(n)


def async_replay(n: Optional[int], fn: Callable[..., Any], *args: Any,
                 retry_on: Optional[tuple] = None,
                 on_retry: Optional[Callable[[int, BaseException],
                                             None]] = None,
                 backoff_s: float = 0.0,
                 backoff_factor: float = 2.0,
                 max_backoff_s: float = 1.0,
                 **kwargs: Any) -> Future:
    """Run fn; on exception re-run, up to n attempts total
    (``n=None`` reads the hpx.resiliency.replay_default_n knob).

    Grown the `sync_replay` policy knobs (typed ``retry_on`` filter,
    ``on_retry`` repair hook, exponential ``backoff_s``) so the
    distributed send path (`dist.actions.resilient_action`) can route
    its bounded retry through the one replay implementation. With no
    policy kwargs this is the classic reference-shaped replay."""
    n = _resolve_n(n)
    if retry_on is None and on_retry is None and backoff_s == 0.0:
        return async_(_replay_loop, n, None, fn, args, kwargs)
    return async_(sync_replay, n, fn, *args,
                  retry_on=retry_on or (Exception,), on_retry=on_retry,
                  backoff_s=backoff_s, backoff_factor=backoff_factor,
                  max_backoff_s=max_backoff_s, **kwargs)


def async_replay_validate(n: Optional[int], validate: Callable[[Any], bool],
                          fn: Callable[..., Any], *args: Any,
                          **kwargs: Any) -> Future:
    """Re-run until validate(result) is truthy, up to n attempts."""
    return async_(_replay_loop, _resolve_n(n), validate, fn, args, kwargs)


def sync_replay(n: Optional[int], fn: Callable[..., Any], *args: Any,
                retry_on: tuple = (Exception,),
                on_retry: Optional[Callable[[int, BaseException],
                                            None]] = None,
                backoff_s: float = 0.0,
                backoff_factor: float = 2.0,
                max_backoff_s: float = 1.0,
                **kwargs: Any) -> Any:
    """Policy-carrying synchronous replay — `_replay_loop` grown the
    three knobs a RECOVERING caller (vs a merely retrying one) needs:

    * ``retry_on`` — only these exception types are transient; anything
      else propagates immediately (a logic bug must not be retried into
      n copies of itself). AbortReplayException always propagates.
    * ``on_retry(attempt, exc)`` — runs BEFORE each re-attempt; this is
      where the serving loop repairs state (restore slots from
      checkpoints) so the replay hits a consistent world. If repair
      itself raises, that propagates: retrying on broken state would
      corrupt, not recover.
    * ``backoff_s`` — exponential backoff between attempts
      (``backoff_s * backoff_factor**i``, capped at ``max_backoff_s``),
      slept via the cooperative `suspend` so an hpx-thread caller
      yields its worker instead of blocking it (and so this stays off
      hpxlint HPX004's raw-time.sleep list).

    Synchronous by design: the serving step IS the caller's loop body —
    wrapping it in a Future (async_replay) would add a pool hop per
    step for nothing.
    """
    from ..exec.execution_base import suspend
    n = _resolve_n(n)
    last_exc: Optional[BaseException] = None
    for attempt in range(n):
        if attempt > 0:
            if backoff_s > 0.0:
                suspend(min(backoff_s * backoff_factor ** (attempt - 1),
                            max_backoff_s))
            if on_retry is not None:
                on_retry(attempt, last_exc)
        try:
            return fn(*args, **kwargs)
        except AbortReplayException:
            raise
        except retry_on as e:
            last_exc = e
    # replay budget exhausted: the caller's recovery could not clear
    # the fault — black-box the moment before the raise unwinds state
    from . import flight
    flight.record_fault("retry-exhausted", site="sync_replay",
                        error=last_exc)
    raise last_exc


# ---------------------------------------------------------------------------
# replicate
# ---------------------------------------------------------------------------

def _values_equal(a: Any, b: Any) -> bool:
    try:
        import numpy as np
        if hasattr(a, "shape") or hasattr(b, "shape"):
            return bool(np.array_equal(np.asarray(a), np.asarray(b)))
    except Exception:  # noqa: BLE001
        pass
    return bool(a == b)


def _replicate_gather(n: int, fn: Callable[..., Any], args: tuple,
                      kwargs: dict) -> List[Future]:
    return [async_(fn, *args, **kwargs) for _ in range(n)]


def _elect(futs: List[Future],
           validate: Optional[Callable[[Any], bool]],
           vote: Optional[Callable[[List[Any]], Any]]) -> Any:
    when_all(futs).get()
    goods: List[Any] = []
    last_exc: Optional[BaseException] = None
    for f in futs:
        try:
            v = f.get()
        except AbortReplicateException:
            raise
        except BaseException as e:  # noqa: BLE001
            last_exc = e
            continue
        if validate is None or validate(v):
            goods.append(v)
    if not goods:
        if last_exc is not None:
            raise last_exc
        raise ReplicateVotingError("no replica produced a valid result")
    if vote is not None:
        return vote(goods)
    return goods[0]


def async_replicate(n: int, fn: Callable[..., Any], *args: Any,
                    **kwargs: Any) -> Future:
    """n concurrent copies; first successful result wins."""
    futs = _replicate_gather(n, fn, args, kwargs)
    return async_(_elect, futs, None, None)


def async_replicate_validate(n: int, validate: Callable[[Any], bool],
                             fn: Callable[..., Any], *args: Any,
                             **kwargs: Any) -> Future:
    futs = _replicate_gather(n, fn, args, kwargs)
    return async_(_elect, futs, validate, None)


def majority_vote(values: List[Any]) -> Any:
    """Default voter: the most frequent value (ties -> first seen)."""
    best, best_count = None, -1
    for i, v in enumerate(values):
        c = sum(1 for w in values if _values_equal(v, w))
        if c > best_count:
            best, best_count = v, c
    if best_count * 2 <= len(values) and len(values) > 2:
        raise ReplicateVotingError(
            f"no majority among {len(values)} replicas")
    return best


def async_replicate_vote(n: int, vote: Callable[[List[Any]], Any],
                         fn: Callable[..., Any], *args: Any,
                         **kwargs: Any) -> Future:
    futs = _replicate_gather(n, fn, args, kwargs)
    return async_(_elect, futs, None, vote)


# ---------------------------------------------------------------------------
# executor wrappers (replay_executor / replicate_executor)
# ---------------------------------------------------------------------------

class ReplayExecutor:
    """Wraps an executor; every async_execute is replayed on failure."""

    def __init__(self, n: int, executor: Any = None,
                 validate: Optional[Callable[[Any], bool]] = None) -> None:
        from ..exec.executors import ParallelExecutor
        self.n = n
        self.validate = validate
        self.executor = executor or ParallelExecutor()

    def _attempts(self, fn: Callable[..., Any], args: tuple,
                  kwargs: dict) -> Any:
        """Host-side replay loop; each ATTEMPT goes through the wrapped
        executor (so a TpuExecutor compiles fn, not the loop — passing
        the loop itself into a compiling executor would trace Python
        callables as jit arguments and always fail)."""
        last_exc: Optional[BaseException] = None
        for _attempt in range(self.n):
            try:
                result = self.executor.async_execute(
                    fn, *args, **kwargs).get()
            except AbortReplayException:
                raise
            except BaseException as e:  # noqa: BLE001
                last_exc = e
                continue
            if self.validate is None or self.validate(result):
                return result
            last_exc = None
        if last_exc is not None:
            raise last_exc
        raise ReplayValidationError(self.n)

    def async_execute(self, fn: Callable[..., Any], *args: Any,
                      **kwargs: Any) -> Future:
        return async_(self._attempts, fn, args, kwargs)

    def sync_execute(self, fn: Callable[..., Any], *args: Any,
                     **kwargs: Any) -> Any:
        return self._attempts(fn, args, kwargs)

    def post(self, fn: Callable[..., Any], *args: Any, **kwargs: Any) -> None:
        # real fire-and-forget: async_ here would drop the future AND
        # the exception it carries (hpxlint HPX003 caught this)
        _post(self._attempts, fn, args, kwargs)


class ReplicateExecutor:
    """Wraps an executor; every async_execute runs n replicas + election."""

    def __init__(self, n: int, executor: Any = None,
                 validate: Optional[Callable[[Any], bool]] = None,
                 vote: Optional[Callable[[List[Any]], Any]] = None) -> None:
        from ..exec.executors import ParallelExecutor
        self.n = n
        self.validate = validate
        self.vote = vote
        self.executor = executor or ParallelExecutor()

    def async_execute(self, fn: Callable[..., Any], *args: Any,
                      **kwargs: Any) -> Future:
        futs = [self.executor.async_execute(fn, *args, **kwargs)
                for _ in range(self.n)]
        return async_(_elect, futs, self.validate, self.vote)

    def sync_execute(self, fn: Callable[..., Any], *args: Any,
                     **kwargs: Any) -> Any:
        return self.async_execute(fn, *args, **kwargs).get()


# ---------------------------------------------------------------------------
# distributed replay: retarget other localities per attempt
# ---------------------------------------------------------------------------

def async_replay_distributed(n: int, action: Any, *args: Any,
                             localities: Optional[Sequence[int]] = None,
                             validate: Optional[Callable[[Any], bool]] = None,
                             ) -> Future:
    """Attempt the action on a sequence of localities (default: here,
    then the others round-robin); each failure moves to the next
    (libs/full/resiliency_distributed behavior)."""
    from ..dist.actions import async_action
    from ..dist.runtime import find_all_localities, find_here

    if localities is None:
        here = find_here()
        rest = [l for l in find_all_localities() if l != here]
        localities = [here] + rest

    def run() -> Any:
        last_exc: Optional[BaseException] = None
        for attempt in range(n):
            loc = localities[attempt % len(localities)]
            try:
                result = async_action(action, loc, *args).get()
            except AbortReplayException:
                raise
            except BaseException as e:  # noqa: BLE001
                last_exc = e
                continue
            if validate is None or validate(result):
                return result
            last_exc = None
        if last_exc is not None:
            raise last_exc
        raise ReplayValidationError(n)

    return async_(run)
