"""Prefetching device input pipeline — the data-loader component.

Reference analog: HPX ships no ML data loader; the driver's native
inventory names one anyway (SURVEY.md §2.8 table: runtime components
around the compute path). The TPU-native shape: training steps must
never wait on host work, so batches are produced by a HOST iterator
(user code: file reads, tokenization, augmentation) running on its
own producer thread, staged onto the device (or a sharded mesh
placement) AHEAD of consumption, and handed to the step as
already-resident jax.Arrays. jax's async dispatch then overlaps step k
with the device_put of batch k+1 and the host production of k+2 — a
three-stage pipeline from one `for batch in loader:` loop.

Design points:
  * the producer runs on a DEDICATED daemon thread per loader — a
    streaming loop must not time-share a fire-and-forget helper-pool
    slot (two concurrent loaders on a 1-thread pool would deadlock:
    the first holds the thread for its whole lifetime), and loader
    lifetime is governed by the loader, not pool shutdown;
  * a bounded queue provides backpressure (prefetch_depth batches
    resident at once — device memory is the budget);
  * device placement happens on the producer side via device_put with
    an optional NamedSharding, so consumption is a queue pop;
  * exceptions in the producer surface at the consumer's next pop,
    carrying the original traceback; StopIteration ends the stream;
  * leaving iteration EARLY — break, an exception in the loop body,
    `stop()`, or dropping the loader — shuts the producer down at its
    next between-items check without draining the source. A source
    whose own __next__ BLOCKS indefinitely cannot be preempted
    (Python offers no way to interrupt it); its daemon thread lingers
    until the source yields or the process exits — bound your
    source's reads if that matters.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterable, Iterator, Optional

__all__ = ["DeviceLoader", "device_loader"]

_STOP = object()


class _Error:
    """Private in-band error envelope: detected by isinstance, so a
    user batch that happens to be a 2-tuple (or an array whose __eq__
    broadcasts) can never be mistaken for a producer failure."""
    __slots__ = ("exc",)

    def __init__(self, exc: BaseException) -> None:
        self.exc = exc


def _bounded_put(q: queue.Queue, stop: threading.Event, item: Any) -> bool:
    """Put with backpressure that stays responsive to stop(); returns
    False if the stream was abandoned."""
    while not stop.is_set():
        try:
            q.put(item, timeout=0.1)
            return True
        except queue.Full:
            continue
    return False


def _produce(q: queue.Queue, stop: threading.Event, source: Iterable[Any],
             transform: Optional[Callable[[Any], Any]],
             sharding: Any) -> None:
    """Producer body. Takes every piece of state BY VALUE — it must
    hold no reference to the DeviceLoader, so an abandoned loader is
    garbage-collectable and its __del__ can stop this loop."""
    import jax
    try:
        for item in source:
            if stop.is_set():
                return
            if transform is not None:
                item = transform(item)
            # device_put traverses pytrees natively (one batched call)
            item = (jax.device_put(item, sharding) if sharding is not None
                    else jax.device_put(item))
            if not _bounded_put(q, stop, item):
                return
    except BaseException as e:  # noqa: BLE001 — surfaces at the pop
        _bounded_put(q, stop, _Error(e))
        return
    _bounded_put(q, stop, _STOP)


class DeviceLoader:
    """Wrap a host batch iterable; iterate device-resident batches.

        loader = DeviceLoader(batches, sharding=NamedSharding(mesh, P("dp")))
        for x in loader:          # x already on device / sharded
            params, loss = step(params, x)

    SINGLE-PASS, like a generator: construct a fresh loader per epoch
    (a second iteration raises). Break out early with `stop()` (or
    just drop the loader — the producer holds no reference to it, so
    garbage collection stops the stream).
    """

    def __init__(self, source: Iterable[Any],
                 sharding: Any = None,
                 prefetch_depth: int = 2,
                 transform: Optional[Callable[[Any], Any]] = None) -> None:
        if prefetch_depth < 1:
            raise ValueError("prefetch_depth >= 1")
        self._source = source
        self._sharding = sharding
        self._transform = transform
        self._q: queue.Queue = queue.Queue(maxsize=prefetch_depth)
        self._stop = threading.Event()
        self._started = False
        self._thread: Optional[threading.Thread] = None

    # -- consumer ----------------------------------------------------------
    def __iter__(self) -> Iterator[Any]:
        if self._started:
            raise RuntimeError(
                "DeviceLoader is single-pass (its source was already "
                "consumed); construct a new loader per epoch")
        self._started = True
        self._thread = threading.Thread(
            target=_produce,
            args=(self._q, self._stop, self._source, self._transform,
                  self._sharding),
            daemon=True, name="hpx-data-loader")
        self._thread.start()
        try:
            while True:
                try:
                    item = self._q.get(timeout=0.1)
                except queue.Empty:
                    if self._stop.is_set():
                        return         # stop() raced an empty queue
                    continue
                if item is _STOP:
                    return
                if isinstance(item, _Error):
                    raise item.exc
                yield item
        finally:
            # generator close (break / exception in the consumer loop)
            # IS stop(): producer exits at its next check AND queued
            # device batches are dropped so HBM frees immediately
            self.stop()

    def stop(self) -> None:
        """Abandon the stream; the producer exits at its next check and
        a consumer blocked on the queue wakes and returns."""
        self._stop.set()
        # unblock a producer stuck on a full queue, then drain AFTER
        # it exits — draining first races a put already past the stop
        # check, which would re-pin one device batch post-drain. The
        # join times out only if the producer is blocked inside the
        # source's own __next__, and the between-items stop check
        # guarantees no further put can follow in that case.
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass

    def __del__(self) -> None:  # best-effort
        try:
            self._stop.set()
        except Exception:  # noqa: BLE001
            pass


def device_loader(source: Iterable[Any], **kwargs: Any) -> DeviceLoader:
    return DeviceLoader(source, **kwargs)
