"""Host-side work-stealing thread pool.

Reference analog: libs/core/thread_pools + libs/core/schedulers
(scheduled_thread_pool running scheduling_loop over per-core queues with
stealing; default local-priority-queue scheduler).

TPU-first rationale: host tasks here are *orchestration* (building dataflow
graphs, dispatching XLA programs, IO) — the FLOPs live on device. The pool
therefore optimizes for low submit overhead and FIFO fairness rather than
cache locality. A native C++ scheduler (hpx_tpu/native) can be swapped in
via the same interface (see exec/ executors); this pure-Python version is
the always-available fallback and the reference for its semantics.

Scheduling: per-worker deques; a worker pops LIFO from its own deque (hot
cache) and steals FIFO from victims — the classic Arora-Blumofe-Plaxton
discipline HPX's `abp` scheduler uses. External submits round-robin across
queues. Idle workers park on a condition, mirroring HPX's scheduling_loop
idle backoff.
"""

from __future__ import annotations

import collections
import itertools
import os
import threading
import time
from typing import Any, Callable, Deque, List, Optional, Tuple

# (fn, args, kwargs) — with an optional 4th slot carrying the causal
# trace context (svc/tracing TaskCtx) while a tracer is active
_Task = Tuple[Callable[..., Any], tuple, dict]

# APEX-style external-timer hook (svc/profiling.py): called with
# (event, fn, seconds-or-None, task_args) at task submit/start/stop when
# set. task_args lets hooks unwrap scheduling shims (e.g. futures'
# _run_into) to attribute time to the user function.
_task_observer: Optional[Callable[..., None]] = None


def set_task_observer(obs: Optional[Callable[..., None]]) -> None:
    global _task_observer
    _task_observer = obs


# Causal-trace capture (svc/tracing): when a tracer is active,
# _trace_submit(fn, args) runs on the SUBMITTING thread and returns the
# span context to thread through to execution (or None); _trace_pending
# parks that context in the worker's thread-local just before the
# observer's start event fires. Both are None when tracing is off — the
# submit hot path pays one global load + is-None test.
_trace_submit: Optional[Callable[..., Any]] = None
_trace_pending: Optional[Callable[..., None]] = None


def set_trace_hooks(submit: Optional[Callable[..., Any]],
                    pending: Optional[Callable[..., None]]) -> None:
    global _trace_submit, _trace_pending
    _trace_submit = submit
    _trace_pending = pending


# Work-helping recursion bound, enforced INSIDE help_one (both pools),
# so every help site — future waits, execution_base yield/suspend/
# yield_while, fork-join latches — is covered. Each nested help is a
# full Python call chain (and on the native pool a C->Python callback
# crossing), so a mass fan-out of tasks that BLOCK (sync remote calls,
# get() inside tasks) would otherwise nest helping until
# RecursionError / C-stack overflow (observed: 2000 blocking component
# calls). At the cap help_one reports "nothing runnable" and waiters
# park — correct whenever the completion arrives from another thread
# (parcel IO thread, device watcher, any worker below the cap), which
# is every legitimate mass-blocking pattern. A PURELY LOCAL serial
# dependency chain deeper than the cap on a LONE worker is the one
# pattern this cannot run; it was already within a few frames of
# crashing the interpreter (~10 stack frames per nested help against
# the default 1000-frame limit).
HELP_DEPTH_CAP = 64
_help_depth = threading.local()


def help_depth() -> int:
    return getattr(_help_depth, "d", 0)


def enter_help() -> bool:
    """True (and one level deeper) when helping may proceed; False at
    the cap. Pair every True with exit_help() in a finally."""
    d = help_depth()
    if d >= HELP_DEPTH_CAP:
        return False
    _help_depth.d = d + 1
    return True


def exit_help() -> None:
    _help_depth.d -= 1


def _note_observer_error() -> None:
    """Swallowed observer exceptions are counted, not lost: the
    /runtime dropped-observer-callbacks counter (svc/profiling) makes
    a broken hook visible. Lazy import — only the rare failure path
    reaches up into svc."""
    try:
        from ..svc.profiling import note_observer_error
        note_observer_error()
    except Exception:  # noqa: BLE001 — accounting must not break tasks
        pass


def notify_submit(fn_args_pairs) -> None:
    """Fire the 'submit' observer event per task; observers must never
    break submission (shared by both pools' submit/submit_many)."""
    obs = _task_observer
    if obs is None:
        return
    for fn, args in fn_args_pairs:
        try:
            obs("submit", fn, None, args)
        except BaseException:  # noqa: BLE001
            _note_observer_error()

# Which pool the current OS thread is a worker of (if any). Futures consult
# this to "work-help" instead of blocking — the analog of an HPX thread
# suspending so its worker can steal other work (libs/core/thread_pools
# scheduling_loop). Without this, a recursive async+get pattern deadlocks
# the moment tasks outnumber workers.
_worker_of = threading.local()


def current_worker_pool() -> Optional["WorkStealingPool"]:
    return getattr(_worker_of, "pool", None)


class WorkStealingPool:
    def __init__(self, num_threads: Optional[int] = None,
                 name: str = "default") -> None:
        self.name = name
        n = num_threads or max(1, (os.cpu_count() or 2))
        self._queues: List[Deque[_Task]] = [collections.deque() for _ in range(n)]
        self._locks = [threading.Lock() for _ in range(n)]
        self._cv = threading.Condition()
        self._idle = 0             # workers parked on _cv
        self._shutdown = False
        self._rr = itertools.count()
        self._tls = threading.local()
        self._workers = [
            threading.Thread(target=self._worker, args=(i,),
                             name=f"hpx-tpu-{name}-{i}", daemon=True)
            for i in range(n)
        ]
        self._executed = 0         # counter surface (perf counters, M9)
        self._stolen = 0
        for w in self._workers:
            w.start()

    # -- submission ---------------------------------------------------------
    def submit(self, fn: Callable[..., Any], *args: Any, **kwargs: Any) -> None:
        """Fire-and-forget schedule (hpx::post semantics at pool level).

        A worker submits to its own queue (children run hot, LIFO — HPX
        thread_queue does the same); external threads round-robin across
        queues."""
        notify_submit([(fn, args)])
        cap = _trace_submit
        tctx = cap(fn, args) if cap is not None else None
        task = (fn, args, kwargs) if tctx is None \
            else (fn, args, kwargs, tctx)
        wid = getattr(self._tls, "wid", None)
        if wid is None:
            wid = next(self._rr) % len(self._queues)
        with self._locks[wid]:
            self._queues[wid].append(task)
        # wake-up fast path: _idle is read WITHOUT the cv lock — a racy
        # miss is bounded by the workers' timed park (they re-scan every
        # 10 ms), while the hit path (no idlers, the high-throughput
        # case) costs zero cv traffic per submit
        if self._idle:
            with self._cv:
                self._cv.notify()

    def submit_many(self, tasks) -> None:
        """Batch fire-and-forget: (fn, args, kwargs) triples appended to
        one queue under one lock with one wake (interface parity with
        NativePool.submit_many; the native path additionally amortizes
        the C-ABI crossing)."""
        tasks = list(tasks)
        if not tasks:
            return
        notify_submit((fn, args) for fn, args, _ in tasks)
        cap = _trace_submit
        if cap is not None:
            # one capture for the whole batch: every task in a fan-out
            # shares the submitting span as its causal parent (the flow
            # arrow lands on the first to run)
            tctx = cap(tasks[0][0], tasks[0][1])
            if tctx is not None:
                rest = type(tctx)(tctx.parent, None, tctx.name)
                tasks = [(fn, args, kw, tctx if i == 0 else rest)
                         for i, (fn, args, kw) in enumerate(tasks)]
        wid = getattr(self._tls, "wid", None)
        if wid is None:
            wid = next(self._rr) % len(self._queues)
        with self._locks[wid]:
            self._queues[wid].extend(tasks)
        if self._idle:
            with self._cv:
                self._cv.notify_all()

    def in_worker(self) -> bool:
        return getattr(self._tls, "wid", None) is not None

    @property
    def num_threads(self) -> int:
        return len(self._queues)

    # -- worker loop --------------------------------------------------------
    def _try_pop(self, wid: int) -> Optional[_Task]:
        q, lk = self._queues[wid], self._locks[wid]
        with lk:
            if q:
                return q.pop()          # own queue: LIFO
        n = len(self._queues)
        for off in range(1, n):
            vid = (wid + off) % n
            with self._locks[vid]:
                if self._queues[vid]:
                    self._stolen += 1
                    return self._queues[vid].popleft()  # steal: FIFO
        return None

    def _run_task(self, task: _Task) -> None:
        fn, args, kwargs = task[0], task[1], task[2]
        obs = _task_observer
        if obs is not None:
            pend = _trace_pending
            if pend is not None:
                # park (or clear) the captured causal context so the
                # tracer's start hook parents this task correctly —
                # always called while tracing is on, so a stale ctx
                # from a previous task can never leak forward
                pend(task[3] if len(task) > 3 else None)
            try:  # observers must never break tasks or kill workers
                obs("start", fn, None, args)
            except BaseException:  # noqa: BLE001
                _note_observer_error()
            t0 = time.monotonic()
        try:
            fn(*args, **kwargs)
        except BaseException:  # noqa: BLE001 — see _worker note
            import traceback
            traceback.print_exc()
        if obs is not None:
            try:
                obs("stop", fn, time.monotonic() - t0, args)
            except BaseException:  # noqa: BLE001
                _note_observer_error()
        self._executed += 1

    def help_one(self) -> bool:
        """Pop and run one queued task from any queue; True if one ran.

        Called by futures while a worker waits — keeps the pool making
        progress instead of deadlocking on nested get() (HPX suspension
        analog). Depth-bounded: at HELP_DEPTH_CAP nested helps this
        reports False so waiters park instead of overflowing the
        stack."""
        if not enter_help():
            return False
        try:
            wid = getattr(self._tls, "wid", 0)
            task = self._try_pop(wid % len(self._queues))
            if task is None:
                return False
            self._run_task(task)
        finally:
            exit_help()
        return True

    def _worker(self, wid: int) -> None:
        self._tls.wid = wid
        _worker_of.pool = self
        park = 0.01
        while True:
            task = self._try_pop(wid)
            if task is None:
                if self._shutdown and not any(self._queues):
                    return
                # timed park with exponential backoff: producers skip
                # the cv entirely unless they see an idler (the racy
                # miss is bounded by this timeout), and a long-idle pool
                # decays to ~2 wakeups/s/worker instead of burning
                # O(threads^2) queue-lock scans at 100 Hz forever;
                # notify still gives instant wakeup normally
                with self._cv:
                    self._idle += 1
                    self._cv.wait(park)
                    self._idle -= 1
                park = min(park * 2, 0.5)
                continue
            park = 0.01
            # task exceptions are captured into futures by callers; a bare
            # submit that raises is a programming error surfaced loudly.
            self._run_task(task)

    # -- lifecycle ----------------------------------------------------------
    def shutdown(self, wait: bool = True) -> None:
        with self._cv:
            self._shutdown = True
            self._cv.notify_all()
        if wait:
            for w in self._workers:
                if w is not threading.current_thread():
                    w.join(timeout=5.0)

    # -- introspection (performance-counter feed) ---------------------------
    def stats(self) -> dict:
        return {"executed": self._executed, "stolen": self._stolen,
                "pending": sum(len(q) for q in self._queues),
                "threads": len(self._queues),
                "idle": self._idle}


_default_pool: Optional[WorkStealingPool] = None
_default_lock = threading.Lock()


def default_pool() -> WorkStealingPool:
    global _default_pool
    if _default_pool is None:
        with _default_lock:
            if _default_pool is None:
                from ..core.config import runtime_config
                _default_pool = WorkStealingPool(
                    runtime_config().os_threads(), "default")
    return _default_pool


def reset_default_pool() -> None:
    global _default_pool
    with _default_lock:
        if _default_pool is not None:
            _default_pool.shutdown(wait=False)
        _default_pool = None
