from .threadpool import WorkStealingPool, default_pool, reset_default_pool  # noqa: F401
