from .threadpool import WorkStealingPool, default_pool, reset_default_pool  # noqa: F401
from .io_service import (  # noqa: F401
    IoServicePool,
    get_io_service_pool,
    io_pool_names,
    io_pool_pending,
)
from .dataloader import DeviceLoader, device_loader  # noqa: F401
