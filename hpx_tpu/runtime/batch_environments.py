"""Batch-scheduler environment detection.

Reference analog: libs/core/batch_environments (detect SLURM/PBS/ALPS
env vars → node list, locality count, rank — SURVEY.md §2.5): an HPX
binary launched under `srun` discovers its localities without flags.
Same here: `detect()` feeds Configuration defaults so `hpx.init()`
under SLURM/PBS/OpenMPI/TPU-pod environments needs no --hpx:* flags.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional

__all__ = ["BatchEnvironment", "detect"]


@dataclass
class BatchEnvironment:
    name: str                       # slurm | pbs | openmpi | tpu | none
    num_localities: Optional[int] = None
    this_locality: Optional[int] = None
    node_list: List[str] = field(default_factory=list)
    extras: Dict[str, str] = field(default_factory=dict)

    def found(self) -> bool:
        return self.name != "none"

    def config_overrides(self) -> Dict[str, str]:
        out: Dict[str, str] = {}
        # only configure a multi-locality launch when the scheduler told
        # us BOTH the world size and OUR rank: inside a bare allocation
        # (salloc without srun) ntasks is set but no per-task rank — a
        # plain `python script.py` there must stay single-locality, not
        # hang waiting for peers that were never launched
        if self.num_localities is not None and self.this_locality is not None:
            out["hpx.localities"] = str(self.num_localities)
            out["hpx.locality"] = str(self.this_locality)
            if self.node_list:
                out["hpx.parcel.address"] = self.node_list[0]
        return out


def _expand_slurm_nodelist(nodelist: str) -> List[str]:
    """Expand 'nid[001-003,007],login1' → node names. Handles the
    common single-bracket form; unexpandable entries pass through."""
    nodes: List[str] = []
    # split on commas not inside brackets
    parts = re.findall(r"[^,\[]+(?:\[[^\]]*\])?", nodelist)
    for part in parts:
        m = re.fullmatch(r"([^\[]+)\[([^\]]+)\]", part)
        if not m:
            if part:
                nodes.append(part)
            continue
        prefix, ranges = m.groups()
        for r in ranges.split(","):
            if "-" in r:
                lo, hi = r.split("-", 1)
                width = len(lo)
                for i in range(int(lo), int(hi) + 1):
                    nodes.append(f"{prefix}{i:0{width}d}")
            else:
                nodes.append(f"{prefix}{r}")
    return nodes


def detect(environ: Optional[Dict[str, str]] = None) -> BatchEnvironment:
    env = os.environ if environ is None else environ

    # SLURM
    if "SLURM_PROCID" in env or "SLURM_JOB_ID" in env:
        be = BatchEnvironment("slurm")
        if "SLURM_NTASKS" in env:
            be.num_localities = int(env["SLURM_NTASKS"])
        elif "SLURM_NNODES" in env:
            be.num_localities = int(env["SLURM_NNODES"])
        if "SLURM_PROCID" in env:
            be.this_locality = int(env["SLURM_PROCID"])
        nl = env.get("SLURM_JOB_NODELIST") or env.get("SLURM_NODELIST")
        if nl:
            be.node_list = _expand_slurm_nodelist(nl)
        return be

    # PBS / Torque
    if "PBS_JOBID" in env:
        be = BatchEnvironment("pbs")
        nodefile = env.get("PBS_NODEFILE")
        if nodefile and os.path.exists(nodefile):
            with open(nodefile) as fh:
                seen: List[str] = []
                for line in fh:
                    n = line.strip()
                    if n and n not in seen:
                        seen.append(n)
                be.node_list = seen
                be.num_localities = len(seen)
        if "PBS_TASKNUM" in env:
            be.this_locality = int(env["PBS_TASKNUM"])
        return be

    # OpenMPI mpirun
    if "OMPI_COMM_WORLD_SIZE" in env:
        return BatchEnvironment(
            "openmpi",
            num_localities=int(env["OMPI_COMM_WORLD_SIZE"]),
            this_locality=int(env.get("OMPI_COMM_WORLD_RANK", 0)))

    # TPU pod (GCE metadata-driven env, jax.distributed conventions)
    if "TPU_WORKER_ID" in env or "CLOUD_TPU_TASK_ID" in env:
        be = BatchEnvironment("tpu")
        wid = env.get("TPU_WORKER_ID") or env.get("CLOUD_TPU_TASK_ID")
        be.this_locality = int(wid)
        hosts = env.get("TPU_WORKER_HOSTNAMES", "")
        if hosts:
            be.node_list = [h.strip() for h in hosts.split(",") if h.strip()]
            be.num_localities = len(be.node_list)
        return be

    return BatchEnvironment("none")
