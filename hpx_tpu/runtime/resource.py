"""Resource partitioner — named pools over host threads AND device sets.

Reference analog: libs/core/resource_partitioner (`hpx::resource::
partitioner`: carve the machine into named thread pools before runtime
start; executors then target a pool — SURVEY.md §2.1).

TPU-first: the machine has TWO resources to carve — host worker threads
(orchestration) and mesh devices (compute). A named pool owns some of
each; `pool.executor()` gives the host executor, `pool.mesh(...)` builds
a jax Mesh over the pool's devices so whole subsystems can be pinned to
a device subset (e.g. an IO pool with 0 devices, a halo pool on one ICI
ring).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence

from ..core.errors import Error, HpxError

__all__ = ["ResourcePartitioner", "Pool", "get_partitioner"]


class Pool:
    def __init__(self, name: str, num_threads: int,
                 devices: Sequence[Any]) -> None:
        self.name = name
        self.num_threads = num_threads
        self.devices = list(devices)
        self._pool = None
        self._lock = threading.Lock()

    # -- host side ----------------------------------------------------------
    def thread_pool(self):
        with self._lock:
            if self._pool is None:
                from .threadpool import WorkStealingPool
                self._pool = WorkStealingPool(self.num_threads, self.name)
            return self._pool

    def executor(self):
        """A ParallelExecutor bound to this pool (the reference's
        pool-per-executor pattern)."""
        from ..exec.executors import ParallelExecutor
        return ParallelExecutor(self.thread_pool())

    # -- device side ---------------------------------------------------------
    def mesh(self, shape: Optional[Sequence[int]] = None,
             axis_names: Sequence[str] = ("x",)):
        if not self.devices:
            raise HpxError(Error.bad_parameter,
                           f"pool '{self.name}' owns no devices")
        from ..parallel.mesh import make_mesh
        if shape is None:
            shape = (len(self.devices),)
        return make_mesh(shape, axis_names, self.devices)

    def shutdown(self) -> None:
        with self._lock:
            if self._pool is not None:
                self._pool.shutdown()
                self._pool = None

    def __repr__(self) -> str:
        return (f"Pool({self.name!r}, threads={self.num_threads}, "
                f"devices={len(self.devices)})")


class ResourcePartitioner:
    """Carve threads/devices into named pools. Remaining resources stay
    in the 'default' pool (reference behavior)."""

    def __init__(self) -> None:
        from ..core.config import runtime_config
        self._total_threads = runtime_config().os_threads()
        self._lock = threading.Lock()
        self._pools: Dict[str, Pool] = {}
        self._assigned_threads = 0
        self._assigned_devices: List[Any] = []
        self._finalized = False

    def create_pool(self, name: str, num_threads: int = 1,
                    devices: Optional[Sequence[Any]] = None) -> None:
        """add_resource analog: claim threads (and optionally devices)
        for a named pool."""
        with self._lock:
            if self._finalized:
                raise HpxError(Error.invalid_status,
                               "partitioner already finalized")
            if name in self._pools or name == "default":
                raise HpxError(Error.bad_parameter,
                               f"pool exists: {name}")
            remaining = self._total_threads - self._assigned_threads
            if num_threads > remaining:
                raise HpxError(
                    Error.bad_parameter,
                    f"pool '{name}' wants {num_threads} threads, only "
                    f"{remaining} of {self._total_threads} unassigned")
            devs = list(devices) if devices else []
            for d in devs:
                if any(d is a for a in self._assigned_devices):
                    raise HpxError(Error.bad_parameter,
                                   f"device {d} already assigned")
            self._pools[name] = Pool(name, num_threads, devs)
            self._assigned_threads += num_threads
            self._assigned_devices.extend(devs)

    def _make_default(self) -> Pool:
        import jax
        leftover_threads = max(
            1, self._total_threads - self._assigned_threads)
        assigned = self._assigned_devices
        devs = [d for d in jax.devices()
                if not any(d is a for a in assigned)]
        return Pool("default", leftover_threads, devs)

    def get_pool(self, name: str = "default") -> Pool:
        with self._lock:
            self._finalized = True
            if name == "default":
                p = self._pools.get("default")
                if p is None:
                    p = self._pools["default"] = self._make_default()
                return p
            p = self._pools.get(name)
        if p is None:
            raise HpxError(Error.bad_parameter, f"no such pool: {name}")
        return p

    def pool_names(self) -> List[str]:
        with self._lock:
            names = list(self._pools)
        if "default" not in names:
            names.append("default")
        return names

    def shutdown(self) -> None:
        with self._lock:
            pools = list(self._pools.values())
            self._pools.clear()
            self._assigned_threads = 0
            self._assigned_devices = []
            self._finalized = False
        for p in pools:
            p.shutdown()


_partitioner: Optional[ResourcePartitioner] = None
_partitioner_lock = threading.Lock()


def get_partitioner() -> ResourcePartitioner:
    global _partitioner
    with _partitioner_lock:
        if _partitioner is None:
            _partitioner = ResourcePartitioner()
        return _partitioner
