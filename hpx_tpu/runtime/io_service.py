"""Helper OS-thread pools for blocking work — the io_service analog.

Reference analog: libs/core/io_service (SURVEY.md §2.1): HPX keeps
small dedicated asio pools ("io", "timer", "parcel") OUTSIDE the
compute workers so blocking syscalls and timer waits never occupy a
scheduler core. Same split here: compute tasks run on the
work-stealing pool (runtime/threadpool.py, native scheduler); BLOCKING
host work — file IO for checkpoints, socket waits, subprocess reaps —
belongs on a named helper pool from this module.

Differences from the compute pool, on purpose:
  * plain FIFO queue, no stealing (helper work is latency-, not
    throughput-bound, and usually blocks);
  * threads are daemons created lazily and sized small (default 1 —
    asio's io_service_pool default);
  * submitting from a helper thread to its own pool is allowed and
    never deadlocks the queue (no work-helping wait() semantics here;
    a Future from a helper pool is waited on from compute threads,
    which DO work-help).

The well-known pool names mirror the reference: "io", "timer",
"parcel". "timer" is registered by core/timing when its deadline
thread starts; "parcel" by native/loader when the epoll endpoint
comes up (external pools: listed and counted, threads owned
elsewhere); "io" is a real submittable pool created on first use.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Callable, Dict, List, Optional

from ..futures.future import Future, SharedState

__all__ = ["IoServicePool", "get_io_service_pool", "io_pool_names",
           "io_pool_pending", "register_external_pool",
           "shutdown_io_pools"]


class IoServicePool:
    """A named pool of daemon OS threads draining a FIFO of callables."""

    def __init__(self, name: str, threads: int = 1) -> None:
        if threads < 1:
            raise ValueError("io pool needs >= 1 thread")
        self.name = name
        self._want = threads
        self._q: collections.deque = collections.deque()
        self._cv = threading.Condition()
        self._threads: List[threading.Thread] = []
        self._stopping = False
        self._started = False

    # -- lifecycle ---------------------------------------------------------
    def _ensure_started(self) -> None:
        with self._cv:
            if self._started or self._stopping:
                return
            self._started = True
            for i in range(self._want):
                t = threading.Thread(target=self._run, daemon=True,
                                     name=f"hpx-io-{self.name}-{i}")
                self._threads.append(t)
                t.start()

    def stop(self, wait: bool = True) -> None:
        with self._cv:
            self._stopping = True
            self._cv.notify_all()
        if wait:
            for t in self._threads:
                if t is not threading.current_thread():
                    t.join(timeout=5.0)

    @property
    def size(self) -> int:
        return self._want

    def pending(self) -> int:
        with self._cv:
            return len(self._q)

    # -- submission --------------------------------------------------------
    def post(self, fn: Callable[..., Any], *args: Any, **kwargs: Any) -> None:
        """Fire-and-forget (hpx::post onto the helper pool)."""
        self._ensure_started()
        with self._cv:
            if self._stopping:
                raise RuntimeError(f"io pool {self.name!r} is stopped")
            self._q.append((fn, args, kwargs, None))
            self._cv.notify()

    def async_execute(self, fn: Callable[..., Any], *args: Any,
                      **kwargs: Any) -> Future:
        """Run on a helper thread; returns a Future (wait for it from a
        COMPUTE thread — those work-help; helper threads should not
        block on their own pool's futures)."""
        self._ensure_started()
        st = SharedState()
        with self._cv:
            if self._stopping:
                raise RuntimeError(f"io pool {self.name!r} is stopped")
            self._q.append((fn, args, kwargs, st))
            self._cv.notify()
        return Future(st)

    # -- worker ------------------------------------------------------------
    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._stopping:
                    self._cv.wait()
                if not self._q:        # stopping and drained
                    return
                fn, args, kwargs, st = self._q.popleft()
            try:
                out = fn(*args, **kwargs)
            except BaseException as e:      # noqa: BLE001
                if st is not None:
                    st.set_exception(e)
                else:
                    # fire-and-forget failures must not vanish (same
                    # policy as the compute pool's _run_task)
                    import traceback
                    traceback.print_exc()
            else:
                if st is not None:
                    st.set_value(out)


class _ExternalPool:
    """Observability shim for pools whose threads live elsewhere (the
    native epoll thread): named, sized, not submittable."""

    def __init__(self, name: str, threads: int, where: str) -> None:
        self.name = name
        self.size = threads
        self.where = where

    def post(self, *a: Any, **k: Any) -> None:
        raise RuntimeError(
            f"pool {self.name!r} is owned by {self.where}; it accepts no "
            f"Python work")

    async_execute = post

    def pending(self) -> int:
        return 0

    def stop(self, wait: bool = True) -> None:
        pass


_POOLS: Dict[str, Any] = {}
_LOCK = threading.Lock()
_DEFAULT_SIZES = {"io": 2, "timer": 1, "parcel": 1}


def get_io_service_pool(name: str = "io",
                        threads: Optional[int] = None) -> IoServicePool:
    """Lazily create (or fetch) the named helper pool. Well-known
    names get reference-matching default sizes; unknown names default
    to 1 thread."""
    with _LOCK:
        pool = _POOLS.get(name)
        if pool is None:
            n = threads if threads is not None else _DEFAULT_SIZES.get(
                name, 1)
            pool = _POOLS[name] = IoServicePool(name, n)
        elif threads is not None and threads != pool.size:
            raise ValueError(
                f"io pool {name!r} already exists with {pool.size} "
                f"thread(s); asked for {threads}")
        return pool


def register_external_pool(name: str, threads: int, where: str) -> None:
    """Record a helper pool whose threads are owned elsewhere (e.g. the
    native epoll thread) so io_pool_names() reflects reality."""
    with _LOCK:
        _POOLS.setdefault(name, _ExternalPool(name, threads, where))


def io_pool_names() -> List[str]:
    with _LOCK:
        return sorted(_POOLS)


def io_pool_pending(name: str) -> int:
    """Queue length of a named pool, 0 when absent/shut down. The
    locked lookup makes this safe to call from perf-counter callbacks
    racing shutdown_io_pools()."""
    with _LOCK:
        pool = _POOLS.get(name)
    return int(pool.pending()) if pool is not None else 0


def shutdown_io_pools() -> None:
    with _LOCK:
        pools = list(_POOLS.values())
        _POOLS.clear()
    for p in pools:
        p.stop(wait=True)
