"""Actions: typed remote-invocable functions.

Reference analog: libs/full/actions + actions_base (HPX_PLAIN_ACTION:
macro-generated action types wrapping a function; direct vs scheduled
execution; typed continuations setting the caller's future).

    @plain_action
    def compute(x, y): ...

    f = hpx.async_action(compute, locality=2, x, y)   # Future
    hpx.post_action(compute, 2, x, y)                 # fire-and-forget

Local destinations take the AGAS-cache fast path: no serialization, the
callable is scheduled directly (SURVEY.md §3.4).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from ..core.errors import BadParameter
from ..futures.future import Future
from ..synchronization import Mutex

_registry: Dict[str, Callable] = {}
_registry_lock = Mutex()


def _qualname(fn: Callable) -> str:
    return f"{fn.__module__}.{fn.__qualname__}"


class Action:
    """A registered remote-invocable function."""

    __slots__ = ("name", "fn", "direct")

    def __init__(self, fn: Callable, name: Optional[str] = None,
                 direct: bool = False) -> None:
        self.fn = fn
        self.name = name or _qualname(fn)
        # direct actions run inline on the parcel-decode path (HPX
        # 'direct' execution for tiny handlers); scheduled ones hop to
        # the task pool.
        self.direct = direct
        with _registry_lock:
            if self.name in _registry and _registry[self.name] is not fn:
                raise BadParameter(f"action name already registered: "
                                   f"{self.name}")
            _registry[self.name] = fn

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        return self.fn(*args, **kwargs)


def plain_action(fn: Callable = None, *, name: Optional[str] = None,
                 direct: bool = False):
    """HPX_PLAIN_ACTION analog (decorator)."""
    if fn is None:
        return lambda f: Action(f, name=name, direct=direct)
    return Action(fn, name=name, direct=direct)


def direct_action(fn: Callable = None, *, name: Optional[str] = None):
    """HPX_PLAIN_DIRECT_ACTION analog."""
    if fn is None:
        return lambda f: Action(f, name=name, direct=True)
    return Action(fn, name=name, direct=True)


def resolve_action(name: str) -> Callable:
    with _registry_lock:
        fn = _registry.get(name)
    if fn is None:
        from ..core.errors import Error, HpxError
        raise HpxError(Error.bad_action_code, f"unknown action: {name}")
    return fn


def async_action(action: Any, locality: int, *args: Any, **kwargs: Any) -> Future:
    """hpx::async(Action{}, id, args...) analog: run on `locality`.

    Fault site "locality": an installed injector raises LocalityLost
    (a NetworkError) here — the send path is where a died worker
    becomes visible to the caller, and NetworkError is what
    `resiliency.async_replay_distributed` retargets on."""
    from ..svc import faultinject
    from .runtime import get_runtime
    faultinject.check("locality", locality=locality)
    return get_runtime().send_action(action, locality, args, kwargs,
                                     want_result=True)


def post_action(action: Any, locality: int, *args: Any, **kwargs: Any) -> None:
    """hpx::post(Action{}, id, args...): fire-and-forget."""
    from ..svc import faultinject
    from .runtime import get_runtime
    faultinject.check("locality", locality=locality)
    get_runtime().send_action(action, locality, args, kwargs,
                              want_result=False)


_idem_counter = 0
_idem_lock = Mutex()


def _next_idem(name: str, locality: int) -> str:
    """Process-unique idempotency key: pid disambiguates localities
    sharing a host, the counter disambiguates calls."""
    import os
    global _idem_counter
    with _idem_lock:
        _idem_counter += 1
        n = _idem_counter
    return f"{os.getpid()}:{name}:{locality}:{n}"


def resilient_action(action: Any, locality: int, *args: Any,
                     timeout_s: Optional[float] = None,
                     retries: int = 3,
                     backoff_s: float = 0.05,
                     idem_key: Optional[str] = None,
                     **kwargs: Any) -> Future:
    """`async_action` with the delivery guarantees remote serving needs:
    per-ATTEMPT timeout, bounded retry with exponential backoff (routed
    through `svc.resiliency.async_replay`), and an idempotency key so a
    retry after a lost ACK is deduplicated by the receiver (the action
    runs at most once; duplicates re-ACK the cached result).

    Retries fire on transient wire trouble — ``NetworkError`` and the
    ``FutureError`` a timed-out ``get()`` raises. A locality the
    failure detector has marked DEAD fast-fails each attempt with
    ``LocalityLost`` (a NetworkError subclass), so exhaustion surfaces
    the typed loss to the caller for failover rather than hanging."""
    from ..core.errors import FutureError, NetworkError
    from ..svc import faultinject
    from ..svc.resiliency import async_replay
    from .runtime import get_runtime
    name = action.name if isinstance(action, Action) else str(action)
    key = idem_key or _next_idem(name, locality)

    def attempt() -> Any:
        faultinject.check("locality", locality=locality)
        fut = get_runtime().send_action(action, locality, args, kwargs,
                                        want_result=True, idem=key)
        return fut.get(timeout_s) if timeout_s is not None else fut.get()

    return async_replay(max(1, retries), attempt,
                        retry_on=(NetworkError, FutureError),
                        backoff_s=backoff_s)
