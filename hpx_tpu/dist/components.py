"""Distributed components: remotely creatable, invocable, migratable objects.

Reference analog: libs/full/components_base + components +
runtime_components (`hpx::components::component_base`, `client_base`,
`HPX_REGISTER_COMPONENT`, `hpx::new_<T>(locality)`, migration via AGAS
pin/unpin — SURVEY.md §2.4) and libs/full/naming (`hpx::id_type`,
`gid_type`).

TPU-first shape:
  - A gid is `(home_locality, type_name, lid)` — stable across
    migrations; AGAS-style resolution maps gid → CURRENT locality
    (local forwarding table first, console KV for migrated objects).
    The reference's 128-bit gid + credit-splitting GC is replaced by
    explicit lifetime (`free()` / `with` scope): a Python control plane
    has no cross-process refcounting to piggyback on, so we make
    destruction explicit instead of pretending.
  - `Component` subclasses are ordinary Python classes registered by
    name (`register_component_type`, the HPX_REGISTER_COMPONENT analog);
    the same code imports on every locality, so the registry is
    rendezvous-free.
  - `new_(Cls, locality, *args)` returns a future<Client>; `Client`
    proxies attribute calls to futures-returning remote invocations
    (client_base's `async`/`sync` spelling both provided).
  - Migration serializes the instance with the parcel serializer (so
    jax.Arrays in component state travel as numpy and are restored on
    the target's device), installs it under the same gid, and leaves a
    forward. Invocations racing a migration chase the forward — the
    parcel layer chains returned futures without blocking a pool thread.

Heavy array state should live in sharded jax.Arrays; components carry
control-plane state (the reference makes the same split between AGAS
objects and the data plane).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional, Tuple, Type

from ..core.errors import Error, HpxError
from ..futures.future import Future, make_ready_future
from .actions import async_action, plain_action, post_action
from .runtime import find_here, get_num_localities
from ..synchronization import Mutex

# ---------------------------------------------------------------------------
# gid / id_type
# ---------------------------------------------------------------------------


class IdType:
    """hpx::id_type analog: names one component instance globally.

    `home` is the creating locality (embedded in the gid like the
    reference's locality bits); resolution to the current locality goes
    through the forwarding layer when the object has migrated.
    """

    __slots__ = ("home", "type_name", "lid")

    def __init__(self, home: int, type_name: str, lid: int) -> None:
        self.home = home
        self.type_name = type_name
        self.lid = lid

    def key(self) -> Tuple[int, str, int]:
        return (self.home, self.type_name, self.lid)

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, IdType) and self.key() == other.key()

    def __hash__(self) -> int:
        return hash(self.key())

    def __repr__(self) -> str:
        return f"IdType({self.type_name}@{self.home}#{self.lid})"

    # pickle support (travels inside parcels / AGAS values)
    def __getstate__(self):
        return self.key()

    def __setstate__(self, st):
        self.home, self.type_name, self.lid = st


# ---------------------------------------------------------------------------
# type registry (HPX_REGISTER_COMPONENT)
# ---------------------------------------------------------------------------

_types: Dict[str, Type] = {}
_types_lock = Mutex()


def register_component_type(cls: Type, name: Optional[str] = None) -> Type:
    """HPX_REGISTER_COMPONENT analog. Usable as a decorator:

        @register_component_type
        class Counter(Component): ...
    """
    n = name or f"{cls.__module__}.{cls.__qualname__}"
    with _types_lock:
        prev = _types.get(n)
        if prev is not None and prev is not cls:
            raise HpxError(Error.duplicate_component_id,
                           f"component type already registered: {n}")
        _types[n] = cls
    cls._component_type_name = n
    return cls


def _resolve_type(name: str) -> Type:
    with _types_lock:
        cls = _types.get(name)
    if cls is None:
        raise HpxError(Error.bad_component_type,
                       f"unknown component type: {name}")
    return cls


class Component:
    """component_base analog. Subclass, register, instantiate with new_.

    Instances get `.gid` after installation. Override __getstate__ /
    __setstate__ for custom migration behavior; by default the instance
    __dict__ travels (minus the gid, which is reassigned on install).
    """

    gid: Optional[IdType] = None

    def on_migrated(self) -> None:
        """Hook: called on the target locality after migration install."""


# ---------------------------------------------------------------------------
# per-locality instance table (the component heap)
# ---------------------------------------------------------------------------

class _Entry:
    __slots__ = ("inst", "pins", "cv", "migrating", "ever_migrated",
                 "freed")

    def __init__(self, inst: Any, ever_migrated: bool = False) -> None:
        self.inst = inst
        self.pins = 0
        self.cv = threading.Condition()
        self.migrating = False
        self.freed = False      # set by _free once the pop is ours
        # True iff this instance arrived via migration: its gid may have
        # forwards/KV entries scattered on other localities that free()
        # must retract
        self.ever_migrated = ever_migrated


_instances: Dict[Tuple[int, str, int], _Entry] = {}
_forwards: Dict[Tuple[int, str, int], int] = {}   # gid key -> locality
_inst_lock = Mutex()
_next_lid = [0]


def _install(gid: IdType, inst: Any, ever_migrated: bool = False) -> None:
    inst.gid = gid
    with _inst_lock:
        _instances[gid.key()] = _Entry(inst, ever_migrated)
        _forwards.pop(gid.key(), None)


def _agas_gid_name(gid: IdType) -> str:
    h, t, l = gid.key()
    return f"/components/where/{h}/{t}/{l}"


def _current_locality(gid: IdType) -> int:
    """Resolve gid → current locality: local table, local forward,
    console KV (set on migration), else home."""
    key = gid.key()
    with _inst_lock:
        if key in _instances:
            return find_here()
        fwd = _forwards.get(key)
    if fwd is not None:
        return fwd
    if get_num_localities() > 1:
        from . import agas
        loc = agas.atomic_read(_agas_gid_name(gid),
                               default=None).get(timeout=30.0)
        if loc is not None:
            return int(loc)
    return gid.home


# ---------------------------------------------------------------------------
# remote operations (actions)
# ---------------------------------------------------------------------------

@plain_action(name="components.create")
def _create(type_name: str, args: tuple, kwargs: dict):
    cls = _resolve_type(type_name)
    inst = cls(*args, **kwargs)
    with _inst_lock:
        lid = _next_lid[0]
        _next_lid[0] += 1
    gid = IdType(find_here(), type_name, lid)
    _install(gid, inst)
    return gid


_MIGRATING = object()          # _pin sentinel: here, but mid-migration


def _pin(gid: IdType):
    """Pin the local instance against migration: the _Entry on success,
    None if the component isn't (or no longer is) here, or the
    _MIGRATING sentinel while a migration is in flight.

    NEVER blocks. The reference's AGAS defers resolution mid-migration
    by suspending the HPX thread; our tasks run on OS pool workers
    (possibly a single one on small hosts), so parking here would
    starve the very pool that must run the migration's install/publish
    steps — the r4 8-locality soak deadlocked exactly that way. Callers
    reschedule instead (see _invoke)."""
    key = gid.key()
    with _inst_lock:
        entry = _instances.get(key)
    if entry is None:
        return None
    with entry.cv:
        if entry.migrating:
            return _MIGRATING
        entry.pins += 1
        return entry


def _unpin(entry: _Entry) -> None:
    with entry.cv:
        entry.pins -= 1
        entry.cv.notify_all()


_MAX_HOPS = 8   # forward-chase TTL: a freed/raced gid must error, not loop


_MAX_MIGRATION_WAITS = 600     # x 50 ms = 30 s of migration patience


@plain_action(name="components.invoke")
def _invoke(gid: IdType, method: str, args: tuple, kwargs: dict,
            _hops: int = 0, _waits: int = 0):
    entry = _pin(gid)
    if entry is _MIGRATING:
        # mid-migration: re-post after a beat instead of parking a pool
        # worker (the timer thread fires the retry; the future chain
        # unwraps through the parcel layer). _waits bounds a stuck
        # migration; _hops is reserved for forward-chases.
        if _waits >= _MAX_MIGRATION_WAITS:
            raise HpxError(Error.invalid_status,
                           f"migration never completed: {gid}")
        from ..core.timing import async_after
        return async_after(
            0.05, _invoke, gid, method, args, kwargs, _hops,
            _waits + 1)
    if entry is None:
        cur = _current_locality(gid)
        if cur != find_here() and _hops < _MAX_HOPS:
            # chase the forward; the parcel layer chains this future
            return async_action(_invoke, cur, gid, method, args, kwargs,
                                _hops=_hops + 1)
        raise HpxError(Error.unknown_component_address,
                       f"component unknown (freed, migrating, or never "
                       f"created): {gid}")
    try:
        return getattr(entry.inst, method)(*args, **kwargs)
    finally:
        _unpin(entry)


@plain_action(name="components.clear_forward")
def _clear_forward(gid: IdType) -> bool:
    with _inst_lock:
        return _forwards.pop(gid.key(), None) is not None


@plain_action(name="components.free")
def _free(gid: IdType, _hops: int = 0) -> bool:
    key = gid.key()
    with _inst_lock:
        entry = _instances.get(key)
    if entry is None:
        cur = _current_locality(gid)
        if cur != find_here() and _hops < _MAX_HOPS:
            return async_action(_free, cur, gid, _hops=_hops + 1)
        with _inst_lock:
            _forwards.pop(key, None)
        return False
    # Mirror _migrate's protocol: an in-flight migration owns the entry
    # (wait for it, then chase the forward it recorded), and pinned
    # invocations must drain before the object dies under them.
    with entry.cv:
        if entry.migrating:
            if not entry.cv.wait_for(lambda: not entry.migrating,
                                     timeout=30.0):
                raise HpxError(Error.invalid_status,
                               f"free raced a stuck migration: {gid}")
            if entry.freed:
                return False    # a concurrent free won the pop
        else:
            entry.migrating = True      # block new pins while freeing
            if not entry.cv.wait_for(lambda: entry.pins == 0,
                                     timeout=30.0):
                entry.migrating = False
                entry.cv.notify_all()
                raise HpxError(Error.invalid_status,
                               f"component stayed pinned: {gid}")
            with _inst_lock:
                _instances.pop(key, None)
                _forwards.pop(key, None)
            entry.freed = True
    if not entry.freed:
        # a migration finished (entry popped + forward recorded) or
        # aborted (instance still resident) — re-resolve from scratch
        return _free(gid, _hops=_hops + 1)
    if get_num_localities() > 1 and entry.ever_migrated:
        # a migrated gid: retract the published location BEFORE replying
        # and clear stale forwards on ALL other localities — any stale
        # forward chain would make later resolutions ping-pong (bounded
        # by the hop TTL, but burning hops and masking the real error).
        # `ever_migrated` (not home != here): an object migrated away
        # and BACK home still has forwards/KV to retract.
        from . import agas
        try:
            agas.unregister_name(_agas_gid_name(gid)).get(timeout=30.0)
        except HpxError:
            pass
        here = find_here()
        for loc in range(get_num_localities()):
            if loc != here:
                post_action(_clear_forward, loc, gid)
    with entry.cv:
        entry.migrating = False
        entry.cv.notify_all()   # wake any _pin waiters; they'll see gone
    return True


@plain_action(name="components.migrate")
def _migrate(gid: IdType, to_loc: int, _hops: int = 0):
    """Runs on the locality currently holding the object.

    Protocol: mark migrating (new invocations block in _pin) → drain
    pins → extract state → install on target + publish location (both
    BEFORE the entry is popped, so blocked invocations released below
    chase a forward that definitely resolves) → pop entry, record
    forward, wake waiters.
    """
    key = gid.key()
    with _inst_lock:
        entry = _instances.get(key)
    if entry is None:
        cur = _current_locality(gid)
        if cur != find_here() and _hops < _MAX_HOPS:
            return async_action(_migrate, cur, gid, to_loc,
                                _hops=_hops + 1)
        raise HpxError(Error.unknown_component_address,
                       f"cannot migrate, no such component here: {gid}")
    if to_loc == find_here():
        return gid
    with entry.cv:
        if entry.migrating:
            raise HpxError(Error.invalid_status,
                           f"concurrent migration in flight: {gid}")
        entry.migrating = True
        # drain pins (reference: AGAS pin count must reach zero)
        if not entry.cv.wait_for(lambda: entry.pins == 0, timeout=30.0):
            entry.migrating = False
            entry.cv.notify_all()
            raise HpxError(Error.invalid_status,
                           f"component stayed pinned: {gid}")
    try:
        state = entry.inst.__getstate__() \
            if hasattr(entry.inst, "__getstate__") \
            else dict(entry.inst.__dict__)
        if isinstance(state, dict):
            state = {k: v for k, v in state.items() if k != "gid"}
        # this action already runs on a pool thread; the remote install
        # and the console publish are straight-line blocking calls
        async_action(_install_migrated, to_loc, gid, gid.type_name,
                     state).get(timeout=30.0)
        if get_num_localities() > 1:
            from . import agas
            agas.register_name(_agas_gid_name(gid), to_loc,
                               allow_replace=True).get(timeout=30.0)
    except BaseException:
        with entry.cv:
            entry.migrating = False
            entry.cv.notify_all()
        raise
    with _inst_lock:
        _instances.pop(key, None)
        _forwards[key] = to_loc
    with entry.cv:
        # clear migrating on the popped entry: a _free blocked on this
        # migration keys off the flag to re-resolve (and _pin waiters
        # re-check the table, see the entry gone, and chase the forward)
        entry.migrating = False
        entry.cv.notify_all()
    return gid


@plain_action(name="components.install_migrated")
def _install_migrated(gid: IdType, type_name: str, state: Any) -> bool:
    cls = _resolve_type(type_name)
    inst = cls.__new__(cls)
    if hasattr(inst, "__setstate__"):
        inst.__setstate__(state)
    else:
        inst.__dict__.update(state)
    _install(gid, inst, ever_migrated=True)
    # plain registered classes (no Component base) migrate too — the
    # hook is optional, like every other part of the component surface
    hook = getattr(inst, "on_migrated", None)
    if hook is not None:
        hook()
    return True


@plain_action(name="components.where")
def _where(gid: IdType) -> int:
    return _current_locality(gid)


@plain_action(name="components.count")
def _component_count(type_name: Optional[str] = None) -> int:
    """Live component instances on this locality (optionally one type) —
    the load feed for binpacked placement (the reference's
    /runtime/count/component@type counter)."""
    with _inst_lock:
        if type_name is None:
            return len(_instances)
        return sum(1 for e in _instances.values()
                   if getattr(type(e.inst), "_component_type_name", None)
                   == type_name)


# ---------------------------------------------------------------------------
# client_base
# ---------------------------------------------------------------------------

class Client:
    """client_base analog: a (serializable) handle to a component.

    c.call('m', *a)  -> Future      (hpx::async(m_action, id, a...))
    c.sync('m', *a)  -> value
    c.post('m', *a)  -> None        (fire-and-forget)
    c.m(*a)          -> Future      (attribute sugar)
    """

    __slots__ = ("gid",)

    def __init__(self, gid: IdType) -> None:
        self.gid = gid

    def _target(self) -> int:
        """Cheap placement guess — local knowledge only, NO console
        roundtrip (that would serialize every invocation through the
        console). Wrong guesses cost one forward-chase hop in _invoke,
        which does the authoritative resolution; this is the AGAS-cache
        fast path of the reference."""
        key = self.gid.key()
        with _inst_lock:
            if key in _instances:
                return find_here()
            fwd = _forwards.get(key)
        return fwd if fwd is not None else self.gid.home

    # -- invocation ---------------------------------------------------------
    def call(self, method: str, *args: Any, **kwargs: Any) -> Future:
        return async_action(_invoke, self._target(), self.gid, method,
                            args, kwargs)

    def sync(self, method: str, *args: Any, **kwargs: Any) -> Any:
        return self.call(method, *args, **kwargs).get()

    def post(self, method: str, *args: Any, **kwargs: Any) -> None:
        post_action(_invoke, self._target(), self.gid, method, args, kwargs)

    def __getattr__(self, name: str) -> Callable[..., Future]:
        if name.startswith("_"):
            raise AttributeError(name)
        return lambda *a, **kw: self.call(name, *a, **kw)

    # -- lifetime / placement ----------------------------------------------
    def where(self) -> Future:
        """Current locality of the component (AGAS resolve analog)."""
        return make_ready_future(_current_locality(self.gid))

    def free(self) -> Future:
        loc = _current_locality(self.gid)
        return async_action(_free, loc, self.gid)

    def __enter__(self) -> "Client":
        return self

    def __exit__(self, *exc: Any) -> None:
        try:
            self.free().get(timeout=30.0)
        except HpxError:
            pass

    # -- misc ---------------------------------------------------------------
    def __eq__(self, other: Any) -> bool:
        return isinstance(other, Client) and self.gid == other.gid

    def __hash__(self) -> int:
        return hash(self.gid)

    def __repr__(self) -> str:
        return f"Client({self.gid!r})"

    def __getstate__(self):
        return self.gid

    def __setstate__(self, gid):
        self.gid = gid


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def new_(cls_or_name: Any, locality: Optional[int] = None,
         *args: Any, **kwargs: Any) -> Future:
    """hpx::new_<T>(locality, args...) analog → future<Client>.

    `locality` may be an int, None (here), or a PlacementPolicy
    (`binpacked()` / `colocated(client)` from dist.distribution_policies
    — the reference's binpacking_/colocating_distribution_policy)."""
    if isinstance(cls_or_name, str):
        type_name = cls_or_name
        _resolve_type(type_name)          # fail fast on unknown types
    else:
        # __dict__ lookup, not getattr: an unregistered SUBCLASS of a
        # registered component would inherit the base's type name and
        # silently instantiate the base class on the target
        type_name = cls_or_name.__dict__.get("_component_type_name")
        if type_name is None:
            raise HpxError(Error.bad_component_type,
                           f"not a registered component type: {cls_or_name} "
                           "(register_component_type first)")
    from .distribution_policies import PlacementPolicy
    if isinstance(locality, PlacementPolicy):
        loc = locality.resolve(1, type_name)[0]
    else:
        loc = find_here() if locality is None else int(locality)
    return async_action(_create, loc, type_name, args, kwargs).then(
        lambda f: Client(f.get()))


def new_sync(cls_or_name: Any, locality: Optional[int] = None,
             *args: Any, **kwargs: Any) -> Client:
    return new_(cls_or_name, locality, *args, **kwargs).get()


def migrate(client: Client, to_locality: int) -> Future:
    """hpx::components::migrate analog → future<Client> (same gid, now
    living on to_locality)."""
    if to_locality < 0 or to_locality >= get_num_localities():
        raise HpxError(Error.bad_parameter,
                       f"no such locality: {to_locality}")
    loc = _current_locality(client.gid)
    # f.get() inside the continuation: a failed migration must fail the
    # returned future, not silently hand back a Client
    return async_action(_migrate, loc, client.gid, to_locality).then(
        lambda f: (f.get(), Client(client.gid))[1])


def async_colocated(action: Any, client: Client, *args: Any,
                    **kwargs: Any) -> Future:
    """hpx::async_colocated analog: run a plain action on whatever
    locality currently hosts the component."""
    return async_action(action, _current_locality(client.gid),
                        *args, **kwargs)


def register_with_basename(basename: str, client: Client,
                           sequence_nr: int = 0) -> Future:
    """hpx::register_with_basename analog (symbol-namespace publish)."""
    from . import agas
    return agas.register_name(f"/basename/{basename}/{sequence_nr}",
                              client)


def find_from_basename(basename: str, sequence_nr: int = 0) -> Future:
    """hpx::find_from_basename analog → future<Client> (waits for the
    publisher, like the reference's rendezvous)."""
    from . import agas
    return agas.resolve_name(f"/basename/{basename}/{sequence_nr}",
                             wait=True)
