"""Distribution policies: where partitioned data lives on the mesh.

Reference analog: libs/full/distribution_policies — `hpx::container_layout
(num_partitions, localities)`, `default_layout`, `binpacking_distribution_
policy`, `target_distribution_policy`. TPU-first reinterpretation: a
"locality" for data placement is a mesh position; a layout names the mesh
axis a container is sharded over and how many partitions it has. XLA/GSPMD
then owns the actual byte placement — the policy only fixes the sharding
spec, which is the whole game on TPU (SURVEY.md §7 design stance).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence


class ContainerLayout:
    """Maps a 1-D container onto a mesh axis.

    num_partitions defaults to the axis size (one partition per device
    along the axis) — HPX's `container_layout(localities)` default. A
    partition count that's a multiple of the axis size gives several
    blocks per device (HPX's `container_layout(n, localities)`).
    """

    def __init__(self, num_partitions: Optional[int] = None,
                 mesh: Any = None, axis: str = "x",
                 targets: Optional[Sequence[Any]] = None) -> None:
        if mesh is None:
            from ..parallel.mesh import make_mesh
            if targets:
                devs = [t.device for t in targets]
                mesh = make_mesh((len(devs),), (axis,), devs)
            else:
                mesh = make_mesh(None, (axis,))  # cached per (shape, axis)
        self.mesh = mesh
        self.axis = axis
        axis_size = mesh.shape[axis]
        self.num_partitions = int(num_partitions or axis_size)
        if self.num_partitions % axis_size != 0 and \
                axis_size % self.num_partitions != 0:
            raise ValueError(
                f"num_partitions={self.num_partitions} incompatible with "
                f"mesh axis '{axis}' of size {axis_size}")

    @property
    def axis_size(self) -> int:
        return int(self.mesh.shape[self.axis])

    def sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P
        return NamedSharding(self.mesh, P(self.axis))

    def replicated_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P
        return NamedSharding(self.mesh, P())

    def __repr__(self) -> str:
        return (f"<ContainerLayout {self.num_partitions} partitions over "
                f"axis '{self.axis}' of {self.mesh.shape}>")


def container_layout(num_partitions: Optional[int] = None,
                     mesh: Any = None, axis: str = "x",
                     targets: Optional[Sequence[Any]] = None
                     ) -> ContainerLayout:
    """hpx::container_layout analog."""
    return ContainerLayout(num_partitions, mesh, axis, targets)


def default_layout(mesh: Any = None) -> ContainerLayout:
    """hpx::container_layout() / default_distribution_policy analog: one
    partition per device over the whole default mesh."""
    return ContainerLayout(mesh=mesh)


def target_layout(targets: Sequence[Any]) -> ContainerLayout:
    """target_distribution_policy analog: place over explicit targets."""
    return ContainerLayout(targets=targets)
