"""Distribution policies: where partitioned data and components live.

Reference analog: libs/full/distribution_policies — `hpx::container_layout
(num_partitions, localities)`, `default_layout`, `binpacking_distribution_
policy`, `colocating_distribution_policy`, `target_distribution_policy`.

TPU-first split into two planes (SURVEY.md §7 design stance):

* DEVICE plane (bulk arrays): a "locality" for data placement is a mesh
  position; ContainerLayout names the mesh axis a container is sharded
  over, and XLA/GSPMD owns the actual byte placement — the policy only
  fixes the sharding spec. Load-based placement makes no sense here
  (SPMD arrays are uniform by construction), so binpacking does not
  apply to ContainerLayout.
* LOCALITY plane (components, control state): PlacementPolicy picks
  host processes for `new_`-created components and for component-backed
  containers (UnorderedMap partitions). `binpacked()` places on the
  least-loaded locality (per-type component count by default, any
  performance counter optionally — the reference's
  binpacking_distribution_policy counter semantics); `colocated(c)`
  places next to an existing component, following migrations.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence


class ContainerLayout:
    """Maps a 1-D container onto a mesh axis.

    num_partitions defaults to the axis size (one partition per device
    along the axis) — HPX's `container_layout(localities)` default. A
    partition count that's a multiple of the axis size gives several
    blocks per device (HPX's `container_layout(n, localities)`).
    """

    def __init__(self, num_partitions: Optional[int] = None,
                 mesh: Any = None, axis: str = "x",
                 targets: Optional[Sequence[Any]] = None) -> None:
        if mesh is None:
            from ..parallel.mesh import make_mesh
            if targets:
                devs = [t.device for t in targets]
                mesh = make_mesh((len(devs),), (axis,), devs)
            else:
                mesh = make_mesh(None, (axis,))  # cached per (shape, axis)
        self.mesh = mesh
        self.axis = axis
        axis_size = mesh.shape[axis]
        self.num_partitions = int(num_partitions or axis_size)
        if self.num_partitions % axis_size != 0 and \
                axis_size % self.num_partitions != 0:
            raise ValueError(
                f"num_partitions={self.num_partitions} incompatible with "
                f"mesh axis '{axis}' of size {axis_size}")

    @property
    def axis_size(self) -> int:
        return int(self.mesh.shape[self.axis])

    def sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P
        return NamedSharding(self.mesh, P(self.axis))

    def replicated_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P
        return NamedSharding(self.mesh, P())

    def __repr__(self) -> str:
        return (f"<ContainerLayout {self.num_partitions} partitions over "
                f"axis '{self.axis}' of {self.mesh.shape}>")


def container_layout(num_partitions: Optional[int] = None,
                     mesh: Any = None, axis: str = "x",
                     targets: Optional[Sequence[Any]] = None
                     ) -> ContainerLayout:
    """hpx::container_layout analog."""
    return ContainerLayout(num_partitions, mesh, axis, targets)


def default_layout(mesh: Any = None) -> ContainerLayout:
    """hpx::container_layout() / default_distribution_policy analog: one
    partition per device over the whole default mesh."""
    return ContainerLayout(mesh=mesh)


def target_layout(targets: Sequence[Any]) -> ContainerLayout:
    """target_distribution_policy analog: place over explicit targets."""
    return ContainerLayout(targets=targets)


# ---------------------------------------------------------------------------
# component placement policies (locality plane)
# ---------------------------------------------------------------------------

class PlacementPolicy:
    """Chooses host localities for components. Accepted wherever new_
    takes a locality; container constructors that place partition
    components (UnorderedMap) take one for all partitions at once."""

    def resolve(self, count: int = 1,
                type_name: Optional[str] = None) -> List[int]:
        raise NotImplementedError


class Binpacked(PlacementPolicy):
    """binpacking_distribution_policy analog: place on the localities
    with the smallest load.

    Load is, per candidate locality, either the component count (of the
    type being created when known — the reference's default
    `/runtime/count/component@type` semantics — else all types), or any
    performance counter: pass `counter=(object, name[, instance])` and
    it is queried remotely on each candidate through the counter
    registry (all queries issued concurrently).

    Multi-placement (count > 1) water-fills: each pick lands on the
    current argmin and then weighs 1.0 there. That is exact when the
    load is in object-count units (the default); with an arbitrary
    counter the weight of one new component in counter units is
    unknowable, so picks repeat the argmin until its counter value is
    overtaken — which IS binpacking, not round-robin: a deeply idle
    locality should absorb the whole batch.
    """

    def __init__(self, localities: Optional[Sequence[int]] = None,
                 counter: Optional[Sequence[str]] = None) -> None:
        self.localities = (None if localities is None
                           else [int(x) for x in localities])
        if counter is not None and not 2 <= len(counter) <= 3:
            raise ValueError(
                "counter must be (object, name) or (object, name, "
                f"instance), got {counter!r}")
        self.counter = None if counter is None else tuple(counter)

    def _loads(self, locs: Sequence[int],
               type_name: Optional[str]) -> List[float]:
        from .actions import async_action
        from .components import _component_count
        if self.counter is None:
            futs = [async_action(_component_count, loc, type_name)
                    for loc in locs]
            return [float(f.get()) for f in futs]
        from ..svc.performance_counters import (counter_name,
                                                query_counter_async)
        obj, cname = self.counter[0], self.counter[1]
        inst = self.counter[2] if len(self.counter) > 2 else "total"
        futs = [query_counter_async(counter_name(obj, cname, inst, loc))
                for loc in locs]
        return [f.get().value for f in futs]

    def resolve(self, count: int = 1,
                type_name: Optional[str] = None) -> List[int]:
        from .runtime import get_num_localities
        locs = (list(range(get_num_localities()))
                if self.localities is None else list(self.localities))
        if not locs:
            raise ValueError("binpacked: no candidate localities")
        loads = self._loads(locs, type_name)
        out = []
        for _ in range(count):
            k = min(range(len(locs)), key=lambda j: (loads[j], locs[j]))
            out.append(locs[k])
            loads[k] += 1.0
        return out


class Colocated(PlacementPolicy):
    """colocating_distribution_policy analog: place on whatever
    locality currently hosts `client`'s component (follows
    migrations — resolution happens at create time)."""

    def __init__(self, client: Any) -> None:
        self.client = client

    def resolve(self, count: int = 1,
                type_name: Optional[str] = None) -> List[int]:
        from .components import _current_locality
        return [_current_locality(self.client.gid)] * count


def binpacked(localities: Optional[Sequence[int]] = None,
              counter: Optional[Sequence[str]] = None) -> Binpacked:
    """hpx::binpacked analog (see Binpacked)."""
    return Binpacked(localities, counter)


def colocated(client: Any) -> Colocated:
    """hpx::colocated analog (see Colocated)."""
    return Colocated(client)
