from . import agas  # noqa: F401
from .actions import (  # noqa: F401
    Action,
    async_action,
    direct_action,
    plain_action,
    post_action,
    resilient_action,
)
from .components import (  # noqa: F401
    Client,
    Component,
    IdType,
    async_colocated,
    find_from_basename,
    migrate,
    new_,
    new_sync,
    register_component_type,
    register_with_basename,
)
from .runtime import (  # noqa: F401
    Runtime,
    finalize,
    find_all_localities,
    find_here,
    find_remote_localities,
    find_root_locality,
    get_num_localities,
    get_runtime,
    init,
)
from .serialization import deserialize, serialize  # noqa: F401
