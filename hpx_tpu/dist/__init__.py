from . import agas  # noqa: F401
from .actions import (  # noqa: F401
    Action,
    async_action,
    direct_action,
    plain_action,
    post_action,
)
from .runtime import (  # noqa: F401
    Runtime,
    finalize,
    find_all_localities,
    find_here,
    find_remote_localities,
    find_root_locality,
    get_num_localities,
    get_runtime,
    init,
)
from .serialization import deserialize, serialize  # noqa: F401
