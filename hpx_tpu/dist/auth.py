"""Parcelport authentication: HMAC challenge-response handshake.

Reference context: HPX's parcelports run on trusted cluster fabrics and
do not authenticate (SURVEY.md §2.4 parcelset row); this runtime's
parcels deserialize via pickle, so an unauthenticated endpoint reachable
from another host would be an arbitrary-code-execution surface (round-2
advisor finding). Fix: before ANY pickled frame is accepted from a
connection, both sides must prove knowledge of a shared secret
(hpx.parcel.secret / HPX_TPU_PARCEL__SECRET) via a mutual HMAC-SHA256
challenge-response:

    dialer  -> HELLO(nonce_c)
    accepter-> REPLY(HMAC(secret, nonce_c || "srv"), nonce_s)
    dialer  -> FINAL(HMAC(secret, nonce_s || "cli"))

Fresh random nonces make the exchange replay-proof. Auth frames are a
FIXED binary format (magic + type + fixed-length fields) parsed with
slicing only — never pickle — so unauthenticated bytes can't reach the
deserializer. Anything malformed or failing verification is dropped;
the peer simply never becomes authenticated.

The handshake authenticates and guards bootstrap; it does not encrypt.
Parcels in flight are as readable as on HPX's fabrics — run multi-node
jobs on a private interconnect, as the reference assumes.
"""

from __future__ import annotations

import hashlib
import hmac as _hmac
from typing import Optional, Tuple

MAGIC = b"HPXA"
T_HELLO = 1
T_REPLY = 2
T_FINAL = 3
NONCE_LEN = 16
MAC_LEN = 32                      # sha256 digest


def mac(secret: str, nonce: bytes, role: bytes) -> bytes:
    """HMAC-SHA256 proof over nonce||role; role separates the two
    directions so a reflected REPLY can't serve as a FINAL."""
    return _hmac.new(secret.encode(), nonce + role,
                     hashlib.sha256).digest()


def verify(expect_mac: bytes, secret: str, nonce: bytes,
           role: bytes) -> bool:
    return _hmac.compare_digest(expect_mac, mac(secret, nonce, role))


def hello_frame(nonce: bytes) -> bytes:
    return MAGIC + bytes([T_HELLO]) + nonce


def reply_frame(mac_: bytes, nonce: bytes) -> bytes:
    return MAGIC + bytes([T_REPLY]) + mac_ + nonce


def final_frame(mac_: bytes) -> bytes:
    return MAGIC + bytes([T_FINAL]) + mac_


def parse(data: bytes) -> Optional[Tuple]:
    """(type, fields...) for a well-formed auth frame, None otherwise.
    Pure slicing on fixed offsets — safe on attacker-controlled bytes."""
    if len(data) < 5 or data[:4] != MAGIC:
        return None
    t = data[4]
    body = data[5:]
    if t == T_HELLO and len(body) == NONCE_LEN:
        return (T_HELLO, body)
    if t == T_REPLY and len(body) == MAC_LEN + NONCE_LEN:
        return (T_REPLY, body[:MAC_LEN], body[MAC_LEN:])
    if t == T_FINAL and len(body) == MAC_LEN:
        return (T_FINAL, body)
    return None
