"""Plugin system: binary filters (parcel compression) and message
coalescing.

Reference analog: libs/core/plugin + libs/full/plugin_factories +
components/parcel_plugins (SURVEY.md §2.5): runtime-registered plugin
factories; binary filters (snappy/zlib/bzip2) compressing parcel
payloads; the message-coalescing plugin batching many small parcels to
the same destination into one wire message.

TPU-first: the parcel plane is the CONTROL plane (bulk data rides ICI),
so filters/coalescing matter for metadata-heavy workloads — thousands
of small actions (AGAS chatter, counter queries, component invokes).
Filters use stdlib/zstd codecs; registration is open (`register_plugin`)
so a deployment can plug its own.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..core.errors import Error, HpxError
from ..synchronization import Mutex

__all__ = [
    "register_plugin", "get_plugin", "list_plugins",
    "BinaryFilter", "get_filter", "Coalescer",
]

# ---------------------------------------------------------------------------
# generic registry (plugin_registry analog)
# ---------------------------------------------------------------------------

_plugins: Dict[Tuple[str, str], Any] = {}
_plugins_lock = Mutex()


def register_plugin(kind: str, name: str, factory: Any,
                    replace: bool = False) -> None:
    with _plugins_lock:
        key = (kind, name)
        if key in _plugins and not replace:
            raise HpxError(Error.bad_plugin_type,
                           f"plugin exists: {kind}/{name}")
        _plugins[key] = factory


def get_plugin(kind: str, name: str) -> Any:
    with _plugins_lock:
        f = _plugins.get((kind, name))
    if f is None:
        raise HpxError(Error.bad_plugin_type,
                       f"no such plugin: {kind}/{name}")
    return f


def list_plugins(kind: Optional[str] = None) -> List[Tuple[str, str]]:
    with _plugins_lock:
        keys = list(_plugins)
    return [k for k in keys if kind is None or k[0] == kind]


# ---------------------------------------------------------------------------
# binary filters (compression)
# ---------------------------------------------------------------------------

class BinaryFilter:
    """A named (compress, decompress) pair. `wire_id` is the single
    byte identifying the filter on the wire; ids must be stable across
    all localities of a run (they share the registration code)."""

    def __init__(self, name: str, wire_id: int,
                 compress: Callable[[bytes], bytes],
                 decompress: Callable[[bytes], bytes]) -> None:
        if not (1 <= wire_id <= 255):
            raise HpxError(Error.bad_parameter, "wire_id must be 1..255")
        self.name = name
        self.wire_id = wire_id
        self.compress = compress
        self.decompress = decompress


_filters_by_id: Dict[int, BinaryFilter] = {}


def _register_filter(f: BinaryFilter) -> None:
    register_plugin("binary_filter", f.name, f)
    _filters_by_id[f.wire_id] = f


def get_filter(name_or_id) -> BinaryFilter:
    if isinstance(name_or_id, int):
        f = _filters_by_id.get(name_or_id)
        if f is None:
            raise HpxError(Error.bad_plugin_type,
                           f"unknown filter wire id: {name_or_id}")
        return f
    return get_plugin("binary_filter", name_or_id)


def _install_builtin_filters() -> None:
    import bz2
    import lzma
    import zlib
    _register_filter(BinaryFilter(
        "zlib", 1, lambda b: zlib.compress(b, 6), zlib.decompress))
    _register_filter(BinaryFilter(
        "bzip2", 2, lambda b: bz2.compress(b, 6), bz2.decompress))
    _register_filter(BinaryFilter(
        "lzma", 3, lambda b: lzma.compress(b, preset=1), lzma.decompress))
    try:
        import zstandard
        c = zstandard.ZstdCompressor(level=3)
        d = zstandard.ZstdDecompressor()
        _register_filter(BinaryFilter(
            "zstd", 4, c.compress,
            lambda b: d.decompress(b, max_output_size=1 << 31)))
    except ImportError:       # pragma: no cover — zstd optional
        pass


_install_builtin_filters()


# wire framing for the parcel layer: 1 header byte (0 = raw, else the
# filter's wire_id), then the (possibly compressed) payload
_RAW = b"\x00"


def encode_payload(data: bytes, filt: Optional[BinaryFilter],
                   min_size: int = 512) -> bytes:
    """Compress when a filter is configured, the payload is big enough
    to matter, and compression actually wins (the reference's filters
    fall back to raw on incompressible data)."""
    if filt is None or len(data) < min_size:
        return _RAW + data
    packed = filt.compress(data)
    if len(packed) + 1 >= len(data):
        return _RAW + data
    return bytes((filt.wire_id,)) + packed


def decode_payload(data: bytes) -> bytes:
    wire_id = data[0]
    if wire_id == 0:
        return data[1:]
    return get_filter(wire_id).decompress(data[1:])


# ---------------------------------------------------------------------------
# message coalescing
# ---------------------------------------------------------------------------

class Coalescer:
    """Batch messages per destination; flush on count, byte budget,
    interval, or explicitly (the parcel coalescing plugin's policy).

    `send_batch(dest, [payload, ...])` is the downstream; payloads keep
    FIFO order per destination.
    """

    def __init__(self, send_batch: Callable[[int, List[Any]], None],
                 max_count: int = 64, max_bytes: int = 1 << 16,
                 interval_s: float = 0.001) -> None:
        self._send = send_batch
        self.max_count = max_count
        self.max_bytes = max_bytes
        self.interval_s = interval_s
        # hpxlint: disable-next=HPX004 — threading.Condition below needs
        # the raw lock object (Mutex has no acquire/release interface)
        self._lock = threading.Lock()
        self._queues: Dict[int, List[Any]] = {}
        self._bytes: Dict[int, int] = {}
        self._deadline: Dict[int, float] = {}
        self._cv = threading.Condition(self._lock)
        self._flusher: Optional[threading.Thread] = None
        self._stop = False
        self.flushes = 0          # perf-counter feeds
        self.coalesced = 0

    def _ensure_flusher(self) -> None:
        if self._flusher is None or not self._flusher.is_alive():
            self._flusher = threading.Thread(
                target=self._flush_loop, name="parcel-coalescer",
                daemon=True)
            self._flusher.start()

    def put(self, dest: int, payload: Any, nbytes: int) -> None:
        out = None
        with self._lock:
            q = self._queues.setdefault(dest, [])
            q.append(payload)
            self._bytes[dest] = self._bytes.get(dest, 0) + nbytes
            self._deadline.setdefault(
                dest, time.monotonic() + self.interval_s)
            self.coalesced += 1
            if (len(q) >= self.max_count
                    or self._bytes[dest] >= self.max_bytes):
                out = self._take_locked(dest)
            else:
                self._ensure_flusher()
                self._cv.notify_all()
        if out:
            self._send(dest, out)

    def _take_locked(self, dest: int) -> List[Any]:
        q = self._queues.pop(dest, [])
        self._bytes.pop(dest, None)
        self._deadline.pop(dest, None)
        if q:
            self.flushes += 1
        return q

    def flush(self, dest: Optional[int] = None) -> None:
        with self._lock:
            dests = [dest] if dest is not None else list(self._queues)
            batches = [(d, self._take_locked(d)) for d in dests]
        for d, batch in batches:
            if batch:
                self._send(d, batch)

    def _flush_loop(self) -> None:
        while True:
            with self._lock:
                if self._stop:
                    return
                now = time.monotonic()
                due = [d for d, t in self._deadline.items() if t <= now]
                batches = [(d, self._take_locked(d)) for d in due]
                if not batches:
                    # nothing due: sleep until the next deadline (or a
                    # put() notifies). Never wait while holding an
                    # un-sent batch — that would add the whole wait to
                    # every interval-triggered flush.
                    if not self._deadline:
                        self._cv.wait(0.05)
                    else:
                        nxt = min(self._deadline.values())
                        self._cv.wait(max(0.0, nxt - time.monotonic()))
            for d, batch in batches:
                if batch:
                    self._send(d, batch)

    def close(self) -> None:
        self.flush()
        with self._lock:
            self._stop = True
            self._cv.notify_all()
