"""Parcel serialization.

Reference analog: libs/core/serialization (input/output archives with
zero-copy `serialize_buffer` chunks for large arrays). TPU-first shape:
pickle protocol 5 with out-of-band buffers — numpy arrays travel as raw
buffer chunks after the pickle stream (no copy into the pickle), the
direct analog of HPX's zero-copy chunk vector. jax.Arrays are converted
to host numpy for the wire (bulk device data should ride ICI collectives
instead — the parcel plane is the control plane) and restored as device
arrays on the receiving side.

Wire format: u32 LE count | u64 LE sizes... | pickle bytes | raw buffers.
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, List, Tuple


class _JaxArrayMarker:
    """Round-trips a jax.Array through numpy across the wire."""

    __slots__ = ("np_value",)

    def __init__(self, np_value) -> None:
        self.np_value = np_value

    def restore(self):
        import jax.numpy as jnp
        return jnp.asarray(self.np_value)


def _rebuild_seq(obj, converted: list):
    """Rebuild a list/tuple preserving subclass (incl. namedtuples)."""
    if isinstance(obj, tuple):
        cls = type(obj)
        if hasattr(cls, "_make"):     # namedtuple
            return cls._make(converted)
        if cls is tuple:
            return tuple(converted)
        try:
            return cls(converted)
        except TypeError:
            return tuple(converted)
    return converted


def _map_tree(obj: Any, leaf) -> Any:
    """Deep map that returns obj UNCHANGED (same identity) when no leaf
    conversion happened — pickle then round-trips exotic containers
    untouched."""
    new = leaf(obj)
    if new is not obj:
        return new
    if isinstance(obj, (list, tuple)):
        converted = [_map_tree(x, leaf) for x in obj]
        if all(a is b for a, b in zip(converted, obj)):
            return obj
        return _rebuild_seq(obj, converted)
    if isinstance(obj, dict):
        converted = {k: _map_tree(v, leaf) for k, v in obj.items()}
        if all(converted[k] is obj[k] for k in obj):
            return obj
        return converted
    return obj


def _encode_jax(obj: Any) -> Any:
    """Deep-convert jax arrays (the only non-picklable payload we bless)."""
    import jax
    import numpy as np

    def leaf(x):
        if isinstance(x, jax.Array):
            return _JaxArrayMarker(np.asarray(x))
        return x

    return _map_tree(obj, leaf)


def _decode_jax(obj: Any) -> Any:
    def leaf(x):
        if isinstance(x, _JaxArrayMarker):
            return x.restore()
        return x

    return _map_tree(obj, leaf)


def serialize(obj: Any) -> bytes:
    buffers: List[pickle.PickleBuffer] = []
    payload = pickle.dumps(_encode_jax(obj), protocol=5,
                           buffer_callback=buffers.append)
    raws = [b.raw() for b in buffers]
    header = struct.pack("<I", len(raws)) + b"".join(
        struct.pack("<Q", len(r)) for r in raws)
    # pickle length so the decoder can split
    header += struct.pack("<Q", len(payload))
    return header + payload + b"".join(bytes(r) for r in raws)


def deserialize(data: bytes) -> Any:
    off = 0
    (nbuf,) = struct.unpack_from("<I", data, off)
    off += 4
    sizes = []
    for _ in range(nbuf):
        (s,) = struct.unpack_from("<Q", data, off)
        sizes.append(s)
        off += 8
    (plen,) = struct.unpack_from("<Q", data, off)
    off += 8
    payload = data[off:off + plen]
    off += plen
    buffers = []
    for s in sizes:
        buffers.append(data[off:off + s])
        off += s
    return _decode_jax(pickle.loads(payload, buffers=buffers))
