"""Distributed runtime: localities, bootstrap, parcel handling.

Reference analog: libs/full/runtime_distributed + init_runtime (the
startup state machine; console locality 0 bootstraps AGAS; workers
register — SURVEY.md §3.1) and libs/full/parcelset (parcelhandler).

Topology: locality = OS process. Locality 0 ("console", HPX's term) is
the bootstrap rendezvous: workers connect to its endpoint, send a hello
carrying their own listen port, receive the full peer table once all
have arrived, then build the full mesh (each locality dials every
lower-numbered peer; the accept side learns who called from an ident
frame). Compute-plane data does NOT travel here — that is jax's job over
ICI; this is the control plane for actions, AGAS and rendezvous.

Single-locality mode (the default) starts no networking at all.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

from ..core.config import Configuration, runtime_config, set_runtime_config
from ..core.errors import Error, HpxError, LocalityLost, NetworkError
from ..futures.future import Future, SharedState, make_ready_future
from .actions import Action, resolve_action
from .serialization import deserialize, serialize

# message tags
_HELLO = "hello"      # (tag, locality, reachable_host, listen_port)
_TABLE = "table"      # (tag, {locality: (host, port)})
_IDENT = "ident"      # (tag, locality)
_PARCEL = "parcel"    # (tag, action_name, args, kwargs, req_id, src_loc
#                        [, idem_key])  — 7th element optional (compat)
_RESULT = "result"    # (tag, req_id, ok, payload)
_BATCH = "batch"      # (tag, [msg, ...])  — coalesced parcels
_CONNECT = "connect"  # (tag, reachable_host, listen_port) — late join
_WELCOME = "welcome"  # (tag, assigned_locality, table)
_PING = "ping"        # (tag, src_locality) — heartbeat probe
_PONG = "pong"        # (tag, src_locality) — heartbeat reply

# failure-detector states (heartbeat loop promotes ALIVE→SUSPECT→DEAD;
# DEAD is terminal — a locality never resurrects under one runtime)
ALIVE = "ALIVE"
SUSPECT = "SUSPECT"
DEAD = "DEAD"


class Runtime:
    def __init__(self, cfg: Configuration) -> None:
        self.cfg = cfg
        self.locality = cfg.get_int("hpx.locality", 0)
        self.num_localities = cfg.get_int("hpx.localities", 1)
        self._endpoint = None
        self._peer_of_loc: Dict[int, int] = {}
        self._loc_of_peer: Dict[int, int] = {}
        self._routes_cv = threading.Condition()
        self._table: Dict[int, Tuple[str, int]] = {}
        self._table_ready = threading.Event()
        self._hellos: Dict[int, Tuple[str, int]] = {}
        self._boot_lock = threading.Lock()
        self._pending: Dict[int, SharedState] = {}
        self._pending_dst: Dict[int, int] = {}   # req_id -> dst locality
        self._pending_lock = threading.Lock()
        self._next_req = 0
        self._wire_lock = threading.Lock()
        self._stopped = False

        # failure detector: heartbeat thread pings every wired peer;
        # missed pongs promote ALIVE→SUSPECT→DEAD. hpx.dist.heartbeat_
        # interval=0 (the default) disables the whole machinery.
        self._hb_interval = cfg.get_float("hpx.dist.heartbeat_interval",
                                          0.0)
        self._hb_suspect = cfg.get_float("hpx.dist.heartbeat_suspect",
                                         2.0)   # intervals w/o pong
        self._hb_dead = cfg.get_float("hpx.dist.heartbeat_dead", 4.0)
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        self._last_pong: Dict[int, float] = {}   # loc -> monotonic time
        self._hb_send_misses = 0                 # failed ping sends
        self._peer_state: Dict[int, str] = {}    # loc -> ALIVE/SUSPECT/DEAD
        self._dead: set = set()
        self._death_listeners: list = []
        # injected net.partition is sticky: once the link to a locality
        # tears, every later message both ways is dropped (the detector
        # then promotes it DEAD like a real partition would)
        self._partitioned: set = set()
        # idempotent parcel delivery: idem_key -> entry dict (done flag,
        # cached ok/value, waiters to re-ack). Duplicates re-reply the
        # cached result — acked and dropped, never re-executed.
        self._idem: Dict[str, dict] = {}
        self._idem_order: list = []              # FIFO for table bound
        self._idem_max = cfg.get_int("hpx.dist.idem_table_max", 4096)
        self._inflight = 0            # parcel handlers not yet replied
        self._inflight_cv = threading.Condition()
        self.parcels_sent = 0         # perf-counter feeds
        self.parcels_received = 0
        self.bytes_sent = 0
        self.bytes_received = 0

        # parcel auth (advisor r2: parcels deserialize via pickle, so an
        # unauthenticated reachable endpoint = remote code execution).
        # When a secret is configured, EVERY connection must complete the
        # HMAC handshake (dist/auth.py) before any frame is unpickled.
        self._secret = cfg.get("hpx.parcel.secret", "")
        self._authed: set = set()             # peer ids past handshake
        self._auth_events: Dict[int, threading.Event] = {}
        self._cli_nonce: Dict[int, bytes] = {}
        self._srv_nonce: Dict[int, bytes] = {}
        self._auth_lock = threading.Lock()

        # plugins: binary filter (parcel compression) + coalescing
        from .plugins import Coalescer, get_filter
        fname = cfg.get("hpx.parcel.compression", "")
        self._filter = get_filter(fname) if fname else None
        self._filter_min = cfg.get_int("hpx.parcel.compression_min_bytes",
                                       512)
        self._coalescer = None
        if cfg.get_bool("hpx.parcel.coalescing", False):
            self._coalescer = Coalescer(
                self._send_batch,
                max_count=cfg.get_int("hpx.parcel.coalescing_count", 64),
                max_bytes=cfg.get_int("hpx.parcel.coalescing_bytes",
                                      1 << 16),
                interval_s=cfg.get_float(
                    "hpx.parcel.coalescing_interval", 0.001))

        if cfg.get_bool("hpx.connect", False):
            # hpx::start + --hpx:connect analog (SURVEY §5.3): join a
            # RUNNING job after bootstrap; locality id assigned by the
            # console at welcome
            self._connect_join()
        elif self.num_localities > 1:
            self._bootstrap()
        if self._hb_interval > 0 and self.num_localities > 1:
            self._start_heartbeat()

    # -- bootstrap ----------------------------------------------------------
    def _reachable_host(self, root_host: str, root_port: int) -> str:
        """The address peers can dial us on: the local interface used to
        reach the console (UDP-connect trick; no packet is sent)."""
        import socket
        try:
            with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
                s.connect((root_host, root_port or 1))
                return s.getsockname()[0]
        except OSError:
            return "127.0.0.1"

    def _root_endpoint_config(self):
        """(root_host, root_port, multi_node) + the security gate shared
        by _bootstrap and _connect_join: a non-loopback (or bind-any)
        parcelport REQUIRES the auth secret — parcels deserialize via
        pickle and MUST NOT be reachable unauthenticated (advisor r2)."""
        root_host = self.cfg.get("hpx.parcel.address", "127.0.0.1")
        root_port = self.cfg.get_int("hpx.parcel.port", 7910)
        multi_node = root_host not in ("127.0.0.1", "localhost")
        bind_any = self.cfg.get_bool("hpx.parcel.bind_any", False)
        if ((multi_node or bind_any) and not self._secret
                and not self.cfg.get_bool("hpx.parcel.allow_insecure",
                                          False)):
            raise HpxError(
                Error.bad_parameter,
                "multi-node parcelport requires hpx.parcel.secret "
                "(env HPX_TPU_PARCEL__SECRET): parcels deserialize via "
                "pickle and MUST NOT be reachable unauthenticated. Set "
                "the same secret on every locality, or acknowledge an "
                "isolated fabric with hpx.parcel.allow_insecure=1.")
        return root_host, root_port, multi_node

    def _dial_console(self, root_host: str, root_port: int) -> int:
        """Securely connect to the console, retrying while it boots."""
        deadline = time.monotonic() + self.cfg.get_float(
            "hpx.startup_timeout", 30.0)
        while True:
            try:
                return self._secure_connect(root_host, root_port)
            except OSError:
                if time.monotonic() > deadline:
                    raise NetworkError(
                        f"cannot reach console at {root_host}:{root_port}")
                time.sleep(0.05)

    def _bootstrap(self) -> None:
        from ..native.loader import NetEndpoint

        root_host, root_port, multi_node = self._root_endpoint_config()
        # 0.0.0.0 is explicit opt-in only; multi-node binds the ONE
        # interface that reaches the console (advisor r2: INADDR_ANY
        # exposed the pickle endpoint on every interface).
        bind_any = self.cfg.get_bool("hpx.parcel.bind_any", False)

        if self.locality == 0:
            bind = ("0.0.0.0" if bind_any
                    else root_host if multi_node else "127.0.0.1")
            self._endpoint = NetEndpoint(root_port, self._on_message,
                                         bind=bind)
            with self._boot_lock:
                self._hellos[0] = (root_host, self._endpoint.port)
            # workers may all have said hello before our own entry landed
            self._maybe_broadcast_table()
        else:
            my_host = (self._reachable_host(root_host, root_port)
                       if multi_node else "127.0.0.1")
            bind = "0.0.0.0" if bind_any else my_host
            self._endpoint = NetEndpoint(0, self._on_message, bind=bind)
            pid = self._dial_console(root_host, root_port)
            self._add_route(0, pid)
            self._send_raw(pid, (_HELLO, self.locality, my_host,
                                 self._endpoint.port))

        if not self._table_ready.wait(self.cfg.get_float(
                "hpx.startup_timeout", 30.0)):
            raise HpxError(Error.startup_timed_out,
                           f"locality {self.locality}: bootstrap timed out")

        # full mesh: dial every lower-numbered peer we aren't wired to
        for loc, (host, port) in sorted(self._table.items()):
            if loc >= self.locality or loc in self._peer_of_loc:
                continue
            pid = self._secure_connect(host, port)
            self._add_route(loc, pid)
            self._send_raw(pid, (_IDENT, self.locality))

    def _connect_join(self) -> None:
        """Late-join attach: dial the console of a RUNNING job, receive
        an assigned locality id + the current table, then wire the full
        mesh exactly like a bootstrapped worker. Incumbents learn about
        us from the console's table broadcast plus our IDENT dials."""
        from ..native.loader import NetEndpoint

        root_host, root_port, multi_node = self._root_endpoint_config()
        my_host = (self._reachable_host(root_host, root_port)
                   if multi_node else "127.0.0.1")
        self._endpoint = NetEndpoint(0, self._on_message, bind=my_host)
        pid = self._dial_console(root_host, root_port)
        self._add_route(0, pid)
        self._send_raw(pid, (_CONNECT, my_host, self._endpoint.port))
        if not self._table_ready.wait(self.cfg.get_float(
                "hpx.startup_timeout", 30.0)):
            raise HpxError(Error.startup_timed_out,
                           "late-join: no welcome from console")
        # full mesh: dial every lower-numbered incumbent
        for loc, (host, port) in sorted(self._table.items()):
            if loc >= self.locality or loc in self._peer_of_loc:
                continue
            wpid = self._secure_connect(host, port)
            self._add_route(loc, wpid)
            self._send_raw(wpid, (_IDENT, self.locality))

    def _secure_connect(self, host: str, port: int) -> int:
        """connect() + (when a secret is configured) the blocking HMAC
        handshake — no parcel leaves for this peer until it has proven
        the secret and accepted our proof."""
        pid = self._endpoint.connect(host, port)
        if not self._secret:
            self._authed.add(pid)
            return pid
        import os as _os

        from . import auth
        ev = threading.Event()
        nonce = _os.urandom(auth.NONCE_LEN)
        with self._auth_lock:
            self._auth_events[pid] = ev
            self._cli_nonce[pid] = nonce
        self._endpoint.send(pid, auth.hello_frame(nonce))
        if not ev.wait(self.cfg.get_float("hpx.startup_timeout", 30.0)):
            raise NetworkError(
                f"auth handshake with {host}:{port} timed out "
                f"(secret mismatch?)")
        return pid

    def _handle_auth(self, peer_id: int, data: bytes) -> None:
        """Auth-frame handling for not-yet-authenticated peers. Runs on
        the IO thread; fixed-format parsing only — attacker bytes never
        reach pickle. Malformed/failed frames are dropped and the peer
        stays unauthenticated."""
        import os as _os

        from . import auth
        fr = auth.parse(data)
        if fr is None:
            return
        if fr[0] == auth.T_HELLO:
            nsrv = _os.urandom(auth.NONCE_LEN)
            with self._auth_lock:
                self._srv_nonce[peer_id] = nsrv
            self._endpoint.send(peer_id, auth.reply_frame(
                auth.mac(self._secret, fr[1], b"srv"), nsrv))
        elif fr[0] == auth.T_REPLY:
            with self._auth_lock:
                nonce_cli = self._cli_nonce.pop(peer_id, None)
                ev = self._auth_events.pop(peer_id, None)
            if nonce_cli is None:
                return
            if not auth.verify(fr[1], self._secret, nonce_cli, b"srv"):
                return
            self._endpoint.send(peer_id, auth.final_frame(
                auth.mac(self._secret, fr[2], b"cli")))
            self._authed.add(peer_id)
            if ev is not None:
                ev.set()
        elif fr[0] == auth.T_FINAL:
            with self._auth_lock:
                nsrv = self._srv_nonce.pop(peer_id, None)
            if nsrv is not None and auth.verify(
                    fr[1], self._secret, nsrv, b"cli"):
                self._authed.add(peer_id)

    # -- wire ---------------------------------------------------------------
    def _send_raw(self, peer_id: int, msg: Any) -> None:
        from .plugins import encode_payload
        data = encode_payload(serialize(msg), self._filter,
                              self._filter_min)
        self.parcels_sent += 1          # counter feeds (svc/performance_
        self.bytes_sent += len(data)    # counters.py); GIL-atomic enough
        self._endpoint.send(peer_id, data)

    def _add_route(self, loc: int, peer_id: int) -> None:
        with self._routes_cv:
            self._peer_of_loc[loc] = peer_id
            self._loc_of_peer[peer_id] = loc
            self._routes_cv.notify_all()

    def _send_to_locality(self, loc: int, msg: Any) -> None:
        if loc in self._dead:
            raise LocalityLost(loc, f"locality {loc} is DEAD",
                               "Runtime._send_to_locality")
        from ..svc import faultinject
        if loc in self._partitioned:
            return                      # link torn: silently dropped
        if faultinject.fires("net.partition", locality=loc):
            self._partitioned.add(loc)
            return
        if faultinject.fires("parcel.drop", locality=loc):
            return                      # lost on the wire, no error
        dup = faultinject.fires("parcel.dup", locality=loc)
        if faultinject.fires("parcel.delay", locality=loc):
            from ..exec.execution_base import suspend
            suspend(self.cfg.get_float("hpx.fault.parcel_delay_s", 0.05))
        pid = self._peer_of_loc.get(loc)
        if pid is None:
            # Bootstrap race: higher-numbered localities dial us at their
            # own pace — wait for the route instead of failing a send
            # issued right after init.
            with self._routes_cv:
                if not self._routes_cv.wait_for(
                        lambda: loc in self._peer_of_loc,
                        self.cfg.get_float("hpx.route_timeout", 30.0)):
                    raise NetworkError(f"no route to locality {loc}")
                pid = self._peer_of_loc[loc]
        try:
            self._send_raw(pid, msg)
            if dup:
                self._send_raw(pid, msg)   # injected duplicate delivery
        except OSError as e:
            # the peer's socket is gone — a crashed worker looks like
            # this before the heartbeat notices; promote immediately
            self._mark_dead(loc)
            raise LocalityLost(
                loc, f"send to locality {loc} failed: {e}",
                "Runtime._send_to_locality") from e

    # -- failure detector ---------------------------------------------------
    def _start_heartbeat(self) -> None:
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, name="hpx-heartbeat",
            daemon=True)
        self._hb_thread.start()

    def _heartbeat_loop(self) -> None:
        """OS-thread heartbeat (not a pool task: it must keep beating
        while the pool is saturated — that is exactly when peers look
        slow). Event.wait paces it and doubles as the stop signal."""
        while not self._hb_stop.wait(self._hb_interval):
            now = time.monotonic()
            for loc in list(self._peer_of_loc):
                if loc == self.locality or loc in self._dead:
                    continue
                if loc not in self._last_pong:
                    self._last_pong[loc] = now   # grace from first ping
                try:
                    self._send_to_locality(loc, (_PING, self.locality))
                except (NetworkError, OSError):
                    # counted, not retried: misses accrue via pong age
                    self._hb_send_misses += 1
                age = now - self._last_pong[loc]
                if age > self._hb_dead * self._hb_interval:
                    self._mark_dead(loc)
                elif age > self._hb_suspect * self._hb_interval:
                    self._peer_state[loc] = SUSPECT

    def locality_state(self, loc: int) -> str:
        """ALIVE / SUSPECT / DEAD as the failure detector sees it."""
        if loc in self._dead:
            return DEAD
        return self._peer_state.get(loc, ALIVE)

    def add_death_listener(self, fn: Callable[[int], None]) -> None:
        """`fn(locality)` runs (on the detecting thread) when the
        failure detector promotes a locality to DEAD."""
        self._death_listeners.append(fn)

    def _mark_dead(self, loc: int) -> None:
        """Promote `loc` to DEAD (terminal) and fail every pending
        parcel toward it with typed LocalityLost — callers must see
        'the worker died, fail over', not hang to their timeout."""
        with self._pending_lock:
            if loc in self._dead:
                return
            self._dead.add(loc)
            self._peer_state[loc] = DEAD
            stale = [(rid, self._pending.pop(rid))
                     for rid, dst in list(self._pending_dst.items())
                     if dst == loc and rid in self._pending]
            for rid, _st in stale:
                self._pending_dst.pop(rid, None)
        for _rid, st in stale:
            st.set_exception(LocalityLost(
                loc, f"locality {loc} died with the parcel in flight",
                "Runtime._mark_dead"))
        for fn in list(self._death_listeners):
            try:
                fn(loc)
            except Exception:  # noqa: BLE001 — detector must keep going
                import traceback
                traceback.print_exc()

    def _on_message(self, peer_id: int, data: bytes) -> None:
        """Runs on the IO thread: decode, then dispatch cheaply."""
        self.parcels_received += 1
        self.bytes_received += len(data)
        if self._secret and peer_id not in self._authed:
            # gate BEFORE deserialize: unauthenticated bytes must never
            # reach pickle (that is the whole attack surface)
            self._handle_auth(peer_id, data)
            return
        try:
            from .plugins import decode_payload
            msg = deserialize(decode_payload(data))
        except Exception:  # noqa: BLE001
            import traceback
            traceback.print_exc()
            return
        tag = msg[0]
        if tag == _BATCH:
            # batch payloads are individually serialized blobs (one
            # serialize per parcel at enqueue, not two)
            for blob in msg[1]:
                try:
                    self._dispatch(peer_id, deserialize(blob))
                except Exception:  # noqa: BLE001
                    import traceback
                    traceback.print_exc()
            return
        self._dispatch(peer_id, msg)

    def _dispatch(self, peer_id: int, msg: Any) -> None:
        tag = msg[0]
        if self._partitioned and tag in (_PARCEL, _RESULT, _PING, _PONG):
            # injected partitions are bidirectional: inbound data-plane
            # traffic from a torn link is dropped too
            src = self._loc_of_peer.get(peer_id)
            if src in self._partitioned:
                return
        if tag == _PING:
            try:
                self._send_to_locality(msg[1], (_PONG, self.locality))
            except (NetworkError, OSError):
                pass
            return
        if tag == _PONG:
            self._last_pong[msg[1]] = time.monotonic()
            self._peer_state[msg[1]] = ALIVE
            return
        if tag == _PARCEL:
            self._handle_parcel(msg)
        elif tag == _RESULT:
            _tag, req_id, ok, payload = msg
            with self._pending_lock:
                st = self._pending.pop(req_id, None)
                self._pending_dst.pop(req_id, None)
            if st is not None:
                if ok:
                    st.set_value(payload)
                else:
                    st.set_exception(payload)
        elif tag == _HELLO:
            _tag, loc, host, port = msg
            self._add_route(loc, peer_id)
            with self._boot_lock:
                self._hellos[loc] = (host, port)
            self._maybe_broadcast_table()
        elif tag == _TABLE:
            self._table = msg[1]
            # late joins grow the job: membership follows the table
            self.num_localities = max(self.num_localities,
                                      len(self._table))
            self._table_ready.set()
        elif tag == _IDENT:
            self._add_route(msg[1], peer_id)
        elif tag == _CONNECT:
            self._handle_connect(peer_id, msg)
        elif tag == _WELCOME:
            _tag, loc, table = msg
            self.locality = loc
            self._table = table
            self.num_localities = len(table)
            self._table_ready.set()

    def _handle_connect(self, peer_id: int, msg: Any) -> None:
        """Console side of a late join: assign the next locality id,
        grow the table, welcome the joiner, broadcast the new table to
        every incumbent (their routes to the joiner form lazily from
        its IDENT dials).

        Joins are only admitted AFTER bootstrap completes — a _CONNECT
        racing the initial hellos would otherwise assign a colliding
        id from the still-empty table and corrupt num_localities, so
        early joins are parked on a pool task until the table is up
        (the joiner is dialing a running job; its own welcome timeout
        bounds the wait)."""
        if self.locality != 0:
            return                      # only the console admits joins
        if not self._table_ready.is_set():
            from ..runtime.threadpool import default_pool

            def later() -> None:
                if self._table_ready.wait(self.cfg.get_float(
                        "hpx.startup_timeout", 30.0)):
                    self._handle_connect(peer_id, msg)

            default_pool().submit(later)
            return
        _tag, host, port = msg
        with self._boot_lock:
            new_loc = max(self._table) + 1 if self._table else 1
            self._table[new_loc] = (host, port)
            self.num_localities = max(self.num_localities,
                                      len(self._table))
            table = dict(self._table)
        self._add_route(new_loc, peer_id)
        self._send_raw(peer_id, (_WELCOME, new_loc, table))
        for loc, pid in list(self._peer_of_loc.items()):
            if loc not in (0, new_loc):
                self._send_raw(pid, (_TABLE, table))

    def _maybe_broadcast_table(self) -> None:
        with self._boot_lock:
            if (self._table_ready.is_set()
                    or len(self._hellos) != self.num_localities):
                return
            self._table = dict(self._hellos)
        for wloc, wpid in list(self._peer_of_loc.items()):
            if wloc != 0:
                self._send_raw(wpid, (_TABLE, self._table))
        self._table_ready.set()

    def _reply(self, src_loc: int, req_id, ok: bool, value) -> None:
        try:
            self._send_to_locality(src_loc, (_RESULT, req_id, ok, value))
        except Exception as e:  # noqa: BLE001
            if self._stopped:
                return
            # unserializable result/exception: the caller must still be
            # unblocked — send a stringified error instead of dropping
            try:
                err = HpxError(Error.serialization_error,
                               f"result not serializable: {e!r}; "
                               f"value was {value!r:.200}")
                self._send_to_locality(src_loc, (_RESULT, req_id, False, err))
            except Exception:  # noqa: BLE001
                import traceback
                traceback.print_exc()

    def _handle_parcel(self, msg) -> None:
        # 7-element parcels carry an idempotency key (resilient_action
        # resends); 6-element parcels are the pre-idempotency wire
        # format and still accepted
        _tag, action_name, args, kwargs, req_id, src_loc = msg[:6]
        idem = msg[6] if len(msg) > 6 else None

        if idem is not None:
            with self._pending_lock:
                entry = self._idem.get(idem)
                if entry is None:
                    entry = {"done": False, "ok": True, "value": None,
                             "waiters": []}
                    self._idem[idem] = entry
                    self._idem_order.append(idem)
                    while len(self._idem_order) > self._idem_max:
                        self._idem.pop(self._idem_order.pop(0), None)
                elif entry["done"]:
                    # duplicate of a completed parcel: re-ACK the cached
                    # result, do NOT re-execute (exactly-once effect)
                    if req_id is not None:
                        self._reply(src_loc, req_id, entry["ok"],
                                    entry["value"])
                    return
                else:
                    # duplicate while the original still runs: park the
                    # reply address; the finishing run acks both
                    if req_id is not None:
                        entry["waiters"].append((src_loc, req_id))
                    return

        with self._inflight_cv:
            self._inflight += 1

        def done() -> None:
            with self._inflight_cv:
                self._inflight -= 1
                self._inflight_cv.notify_all()

        def settle(ok: bool, value) -> None:
            """Reply to the original + any duplicate waiters, caching
            the result for later re-deliveries."""
            waiters = [(src_loc, req_id)] if req_id is not None else []
            if idem is not None:
                with self._pending_lock:
                    entry = self._idem.get(idem)
                    if entry is not None:
                        entry["done"] = True
                        entry["ok"] = ok
                        entry["value"] = value
                        waiters += entry.pop("waiters", [])
                        entry["waiters"] = []
            for w_loc, w_req in waiters:
                self._reply(w_loc, w_req, ok, value)

        def run() -> None:
            try:
                fn = resolve_action(action_name)
                value = fn(*args, **kwargs)
            except BaseException as e:  # noqa: BLE001
                settle(False, e)
                done()
                return
            if isinstance(value, Future):
                # continuation, NOT a blocking get(): a wait-style action
                # (agas rendezvous) must not pin a pool thread, or T such
                # parcels deadlock a T-thread pool
                def on_ready(f: Future) -> None:
                    try:
                        if f.has_exception():
                            try:
                                f.get()
                            except BaseException as e:  # noqa: BLE001
                                settle(False, e)
                        else:
                            settle(True, f.get())
                    finally:
                        done()
                # hpxlint: disable=HPX003 — on_ready() is the sink: it
                # replies or forwards the exception; then-future unused
                value.then(on_ready)
                return
            settle(True, value)
            done()

        # scheduled execution on the task pool (HPX: parcel decode
        # schedules an HPX thread; 'direct' actions could run inline but
        # the IO thread must stay responsive)
        from ..runtime.threadpool import default_pool
        default_pool().submit(run)

    # -- public -------------------------------------------------------------
    def send_action(self, action: Any, locality: int, args: tuple,
                    kwargs: dict, want_result: bool,
                    idem: Optional[str] = None) -> Optional[Future]:
        name = action.name if isinstance(action, Action) else str(action)
        if locality == self.locality:
            # local fast path: no serialization (AGAS cache hit analog)
            from ..futures.async_ import async_, post
            fn = resolve_action(name)
            if want_result:
                return async_(fn, *args, **kwargs)
            post(fn, *args, **kwargs)
            return None
        if locality < 0 or locality >= self.num_localities:
            raise HpxError(Error.bad_parameter,
                           f"no such locality: {locality}")
        if locality in self._dead:
            raise LocalityLost(locality,
                               f"locality {locality} is DEAD",
                               "Runtime.send_action")
        req_id = None
        fut = None
        if want_result:
            st: SharedState = SharedState()
            with self._pending_lock:
                req_id = self._next_req
                self._next_req += 1
                self._pending[req_id] = st
                self._pending_dst[req_id] = locality
            fut = Future(st)
        msg = ((_PARCEL, name, args, kwargs, req_id, self.locality)
               if idem is None else
               (_PARCEL, name, args, kwargs, req_id, self.locality,
                idem))
        try:
            if self._coalescer is not None:
                blob = serialize(msg)
                self._coalescer.put(locality, blob, len(blob))
            else:
                self._send_to_locality(locality, msg)
        except BaseException:
            # the parcel never left: un-register it so finalize/death
            # sweeps don't double-fail the future the caller never got
            if req_id is not None:
                with self._pending_lock:
                    self._pending.pop(req_id, None)
                    self._pending_dst.pop(req_id, None)
            raise
        return fut

    def _send_batch(self, loc: int, blobs: list) -> None:
        self._send_to_locality(loc, (_BATCH, blobs))

    def barrier(self, tag: str = "default") -> None:
        """Release barrier: every locality's arrive-action on the console
        completes only when all have arrived (and_gate on the console —
        the reference's collectives barrier shape, SURVEY.md §3.6; the
        full collectives module arrives with M7)."""
        if self.num_localities == 1:
            return
        from .actions import async_action
        # generous default: on a loaded single-core host, N fresh
        # localities importing jax can legitimately stagger by minutes
        async_action("hpx.barrier_arrive", 0, tag,
                     self.num_localities).get(
            self.cfg.get_float("hpx.barrier_timeout", 180.0))

    def finalize(self) -> None:
        """Orderly shutdown: barrier first so no locality closes its
        endpoint while peers still await replies (the classic shutdown-
        ordering trap — SURVEY.md §7)."""
        if self._stopped:
            return
        if self._hb_thread is not None:
            self._hb_stop.set()
            self._hb_thread.join(timeout=2.0)
        if self._coalescer is not None:
            self._coalescer.flush()
        if self.num_localities > 1:
            try:
                self.barrier("__finalize__")
            except Exception:  # noqa: BLE001 — close anyway
                pass
            # drain: replies to peers (e.g. their barrier releases) may
            # still be queued on the pool — closing now would strand them
            with self._inflight_cv:
                self._inflight_cv.wait_for(
                    lambda: self._inflight == 0,
                    self.cfg.get_float("hpx.shutdown_timeout", 10.0))
        self._stopped = True
        if self._coalescer is not None:
            self._coalescer.close()
        if self._endpoint is not None:
            self._endpoint.close()
        # fail anything still awaiting a reply with the TYPED error —
        # a caller blocked on .get() must not hang to its timeout after
        # the endpoint that could have carried the reply is gone
        with self._pending_lock:
            stale = [(rid, st, self._pending_dst.get(rid, -1))
                     for rid, st in self._pending.items()]
            self._pending.clear()
            self._pending_dst.clear()
        for _rid, st, dst in stale:
            st.set_exception(LocalityLost(
                dst, f"runtime finalized with parcel to locality "
                f"{dst} still pending", "Runtime.finalize"))


_runtime: Optional[Runtime] = None
_runtime_lock = threading.Lock()


_counter_print_stop: Optional[Any] = None


def _start_counter_printing(cfg: Configuration) -> None:
    """--hpx:print-counter[-interval] wiring: periodic printing when an
    interval is configured; otherwise a one-shot dump at finalize
    (reference behavior — shutdown counter report)."""
    global _counter_print_stop
    patterns = cfg.get("hpx.counters.print", "")
    interval = cfg.get_float("hpx.counters.print_interval", 0.0)
    if patterns and interval > 0:
        from ..svc.performance_counters import start_counter_printing
        stops = [start_counter_printing(interval, p.strip())
                 for p in patterns.split(",") if p.strip()]

        def stop_all() -> None:
            for s in stops:
                s()
        _counter_print_stop = stop_all


def _finalize_counter_printing(cfg: Configuration) -> None:
    global _counter_print_stop
    if _counter_print_stop is not None:
        _counter_print_stop()
        _counter_print_stop = None
    patterns = cfg.get("hpx.counters.print", "")
    if patterns and cfg.get_float("hpx.counters.print_interval",
                                  0.0) <= 0:
        from ..svc.performance_counters import print_counters
        for p in patterns.split(","):
            if p.strip():
                try:
                    print_counters(p.strip())
                except Exception:  # noqa: BLE001 — shutdown must proceed
                    pass


def init(argv: Optional[list] = None,
         overrides: Optional[dict] = None) -> Runtime:
    """hpx::init analog (explicit; single-locality implicit via
    get_runtime)."""
    global _runtime
    with _runtime_lock:
        if _runtime is not None:
            return _runtime
        cfg = Configuration(argv=argv, overrides=overrides)
        if cfg.get_bool("hpx.diagnostics.dump_config"):
            # --hpx:dump-config: print the fully-resolved configuration
            # (HPX prints its merged ini at startup under the same flag)
            import sys
            print(cfg.dump(), file=sys.stderr)
        set_runtime_config(cfg)
        _runtime = Runtime(cfg)
        _start_counter_printing(cfg)
        return _runtime


def get_runtime() -> Runtime:
    global _runtime
    if _runtime is None:
        with _runtime_lock:
            if _runtime is None:
                _runtime = Runtime(runtime_config())
    return _runtime


def finalize() -> None:
    """hpx::finalize analog."""
    global _runtime
    with _runtime_lock:
        if _runtime is not None:
            _finalize_counter_printing(_runtime.cfg)
            _runtime.finalize()
            _runtime = None
            set_runtime_config(None)


# -- locality API (hpx::find_here etc.) -------------------------------------

def find_here() -> int:
    return get_runtime().locality


def find_all_localities() -> list:
    return list(range(get_runtime().num_localities))


def find_remote_localities() -> list:
    rt = get_runtime()
    return [i for i in range(rt.num_localities) if i != rt.locality]

def find_root_locality() -> int:
    return 0


def get_num_localities() -> int:
    return get_runtime().num_localities


# -- console-side barrier state (release barrier) ---------------------------

_barrier_lock = threading.Lock()
_barrier_state: Dict[str, list] = {}  # tag -> [count, [SharedStates]]


def _barrier_arrive(tag: str, n: int):
    """Console action: returns a future released when n arrivals reached.

    Each generation of a tag is independent: once released, the state is
    cleared so the same tag can barrier again."""
    st = SharedState()
    with _barrier_lock:
        count, waiters = _barrier_state.setdefault(tag, [0, []])
        _barrier_state[tag][0] += 1
        waiters.append(st)
        if _barrier_state[tag][0] >= n:
            released = waiters[:]
            del _barrier_state[tag]
        else:
            released = None
    if released:
        for w in released:
            w.set_value(True)
    return Future(st)


from .actions import plain_action as _pa  # noqa: E402
_pa(_barrier_arrive, name="hpx.barrier_arrive")
