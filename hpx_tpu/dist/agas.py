"""AGAS-lite: the name/symbol service.

Reference analog: libs/full/agas — of HPX's four namespaces, the TPU
runtime needs two for real (SURVEY.md §2.8 mapping):
  * locality namespace -> the runtime's peer table (dist/runtime.py)
  * symbol namespace   -> THIS module: name -> value registry hosted on
    the console locality (locality 0), used for collective rendezvous
    (M7), distributed-object registration, and barriers.
The primary/component namespaces (128-bit gids, credit GC) collapse away:
single-controller jax arrays don't need global addresses, and distributed
objects are (locality, name) pairs.

All functions return Futures (AGAS requests are remote actions).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ..futures.future import Future, make_ready_future
from .actions import async_action, plain_action
from ..synchronization import Mutex

_symbols: Dict[str, Any] = {}
_symbols_lock = Mutex()
_waiters: Dict[str, list] = {}


@plain_action(name="agas.register")
def _register(name: str, value: Any, allow_replace: bool = False) -> bool:
    with _symbols_lock:
        if name in _symbols and not allow_replace:
            return False
        _symbols[name] = value
        waiters = _waiters.pop(name, [])
    for st in waiters:
        st.set_value(value)
    return True


@plain_action(name="agas.resolve")
def _resolve(name: str, wait: bool = False) -> Any:
    """Returns the value; with wait=True, blocks (as a future chain)
    until someone registers the name — the rendezvous primitive."""
    from ..futures.future import SharedState
    with _symbols_lock:
        if name in _symbols:
            return _symbols[name]
        if not wait:
            raise KeyError(name)
        st = SharedState()
        _waiters.setdefault(name, []).append(st)
    return Future(st)  # unwrapped into the action result


@plain_action(name="agas.unregister")
def _unregister(name: str) -> bool:
    with _symbols_lock:
        return _symbols.pop(name, None) is not None


@plain_action(name="agas.incr")
def _incr(name: str, amount: int = 1) -> int:
    with _symbols_lock:
        v = _symbols.get(name, 0) + amount
        _symbols[name] = v
        return v


@plain_action(name="agas.read")
def _read(name: str, default: Any = 0) -> Any:
    with _symbols_lock:
        return _symbols.get(name, default)


# -- client API (hpx::agas::register_name etc.) -----------------------------

def _console() -> int:
    return 0


def register_name(name: str, value: Any,
                  allow_replace: bool = False) -> Future:
    """hpx::register_with_basename / agas::register_name analog."""
    return async_action(_register, _console(), name, value, allow_replace)


def resolve_name(name: str, wait: bool = False) -> Future:
    """agas::resolve_name; wait=True blocks until registered."""
    return async_action(_resolve, _console(), name, wait)


def unregister_name(name: str) -> Future:
    return async_action(_unregister, _console(), name)


def atomic_increment(name: str, amount: int = 1) -> Future:
    return async_action(_incr, _console(), name, amount)


def atomic_read(name: str, default: Any = 0) -> Future:
    return async_action(_read, _console(), name, default)
